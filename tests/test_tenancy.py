"""Secure multi-tenant plane (ISSUE 12): structural namespace isolation,
quota classes riding the governor's priority machinery, per-tenant $SYS
scoping, and the batched per-subscriber re-encryption stage with its
device-vs-host differential oracle and breaker degradation.

The isolation tests drive TWO tenants (plus an untenanted bystander)
through IDENTICAL topic/filter strings — exact, wildcard, $SHARE,
retained, predicated — and assert zero cross-tenant deliveries. The
point is that isolation holds by construction (disjoint trie subtrees),
not by any per-delivery filtering."""

import asyncio
import math
import random

import numpy as np
import pytest

import mqtt_tpu.packets as pkts
from mqtt_tpu.packets import FixedHeader, Packet, Subscription
from mqtt_tpu.server import Options, Server
from mqtt_tpu.tenancy import (
    KeyRegistry,
    RecryptEngine,
    TenantPlane,
    local_client_id,
    scope_client_id,
)
from mqtt_tpu.topics import (
    NS_CHAR,
    is_valid_filter,
    ns_local,
    ns_scope_filter,
    ns_scope_topic,
    ns_tenant,
)
from mqtt_tpu.ops.recrypt import (
    aes_encrypt_blocks,
    ctr_counters,
    expand_key,
    host_keystream,
    keystream_async,
    xor_into,
)

from tests.test_server import (
    Harness,
    pub_packet,
    read_wire_packet,
    run,
    sub_packet,
)

KEY_A = bytes.fromhex("000102030405060708090a0b0c0d0e0f")
KEY_S = bytes.fromhex("2b7e151628aed2a6abf7158809cf4f3c")


def tenant_options(**kw):
    tenants = kw.pop(
        "tenants",
        {
            "acme": {"quota_class": "vip"},
            "bulkco": {"quota_class": "bulk"},
        },
    )
    users = kw.pop(
        "tenant_users",
        {"cidA": "acme", "cidA2": "acme", "cidB": "bulkco", "cidB2": "bulkco"},
    )
    return Options(
        inline_client=False,
        tenancy=True,
        tenants=tenants,
        tenant_users=users,
        **kw,
    )


class TestScoping:
    def test_scope_round_trip(self):
        scoped = ns_scope_topic("acme", "a/b")
        assert scoped == NS_CHAR + "acme/a/b"
        assert ns_tenant(scoped) == "acme"
        assert ns_local(scoped) == "a/b"
        assert ns_local("a/b") == "a/b" and ns_tenant("a/b") == ""

    def test_scope_filter_shapes(self):
        assert ns_scope_filter("t", "#") == NS_CHAR + "t/#"
        assert (
            ns_scope_filter("t", "$SHARE/g/s/#")
            == f"$SHARE/g/{NS_CHAR}t/s/#"
        )
        assert ns_scope_filter("t", "$SYS/broker/tenant/#") == (
            NS_CHAR + "t/$SYS/broker/tenant/#"
        )

    def test_client_id_scoping(self):
        sid = scope_client_id("acme", "dev1")
        assert sid.startswith(NS_CHAR) and local_client_id(sid) == "dev1"

    def test_nul_filters_rejected_on_the_wire(self):
        # [MQTT-4.7.3-2] — and the structural guarantee that a wire
        # topic can never alias into a scoped key
        assert not is_valid_filter(NS_CHAR + "acme/a", False)
        assert not is_valid_filter("a/" + NS_CHAR + "b", True)
        assert is_valid_filter("a/b", True)

    def test_invalid_tenant_names_refused(self):
        plane = TenantPlane()
        for bad in ("", "a/b", "a+", "c#", NS_CHAR + "x"):
            with pytest.raises(ValueError):
                plane.register(bad)

    def test_resolution_order_username_then_cid_then_default(self):
        plane = TenantPlane()
        plane.configure(
            {"t1": {}, "t2": {}, "dflt": {}},
            {"alice": "t1", "cid9": "t2"},
            default="dflt",
        )
        assert plane.resolve("alice", "cid9").name == "t1"
        assert plane.resolve("", "cid9").name == "t2"
        assert plane.resolve("nobody", "cidX").name == "dflt"
        plane2 = TenantPlane()
        plane2.configure({"t1": {}}, {"alice": "t1"}, default="")
        assert plane2.resolve("nobody", "cidX") is None


class TestAESVectors:
    def test_fips_197_c1_block(self):
        rk = expand_key(KEY_A)
        pt = np.frombuffer(
            bytes.fromhex("00112233445566778899aabbccddeeff"), dtype=np.uint8
        ).reshape(1, 16)
        ct = aes_encrypt_blocks(rk[None], pt).tobytes()
        assert ct.hex() == "69c4e0d86a7b0430d8cdb78070b4c55a"

    def test_sp800_38a_f51_ctr_keystream(self):
        # CTR-AES128.Encrypt: the first counter block's keystream XOR
        # the known plaintext block must give the known ciphertext
        rk = expand_key(KEY_S)
        ctr = np.frombuffer(
            bytes.fromhex("f0f1f2f3f4f5f6f7f8f9fafbfcfdfeff"), dtype=np.uint8
        ).reshape(1, 16)
        ks = aes_encrypt_blocks(rk[None], ctr)
        pt1 = bytes.fromhex("6bc1bee22e409f96e93d7e117393172a")
        ct1 = xor_into(pt1, ks)
        assert ct1.hex() == "874d6191b620e3261bef6864990db6ce"

    def test_device_matches_host_across_sizes(self):
        """The seeded device-vs-host differential across payload sizes:
        0, 1, block-aligned, block+1, and 256KiB (ISSUE acceptance)."""
        rng = random.Random(7)
        table = np.stack([expand_key(KEY_A), expand_key(KEY_S)])
        for size in (0, 1, 16, 17, 256 * 1024):
            n_blocks = (size + 15) // 16
            if n_blocks == 0:
                continue  # no keystream to generate at all
            nonce = bytes(rng.randrange(256) for _ in range(12))
            kidx = np.array(
                [rng.randrange(2) for _ in range(n_blocks)], dtype=np.int32
            )
            counters = ctr_counters(nonce, n_blocks)
            resolver = keystream_async(table, kidx, counters)
            assert resolver is not None
            dev = resolver()
            host = host_keystream(table, kidx, counters)
            assert np.array_equal(dev, host), f"mismatch at size {size}"

    def test_engine_seal_open_round_trip_all_sizes(self):
        reg = KeyRegistry()
        reg.set_key("t", "pub", KEY_A)
        reg.set_key("t", "sub", KEY_S)
        eng = RecryptEngine(reg, oracle_sample=1)
        eng.reseed_nonce(b"seed")
        plane = TenantPlane()
        t = plane.register("t", encrypted=("e/",))
        for size in (0, 1, 16, 17, 256 * 1024):
            plaintext = bytes(range(256)) * (size // 256) + bytes(size % 256)
            plaintext = plaintext[:size]
            wire = eng.seal_with_key(KEY_A, plaintext)
            assert len(wire) == 12 + size
            job = eng.decrypt_job(t, ("pub",), wire)
            assert not job.error
            got = eng.open_publish(t, ("pub",), wire, job)
            assert got == plaintext
            sealed = eng.seal_fanout(t, plaintext, [("s1", ("sub",))])
            assert eng.open_with_key(KEY_S, sealed["s1"]) == plaintext
            if size:
                assert sealed["s1"][12:] != plaintext
        assert eng.oracle_mismatches == 0

    def test_staged_issue_batch_attaches_keystreams(self):
        reg = KeyRegistry()
        reg.set_key("t", "pub", KEY_A)
        eng = RecryptEngine(reg, oracle_sample=1, device_min_blocks=1)
        plane = TenantPlane()
        t = plane.register("t", encrypted=("e/",))
        wire = eng.seal_with_key(KEY_A, b"x" * 40)
        jobs = [None, eng.decrypt_job(t, ("pub",), wire), None]
        resolver = eng.issue_batch(jobs)
        assert resolver is not None
        eng.attach(resolver())
        assert jobs[1].keystream is not None
        assert jobs[1].keystream.shape == (3, 16)
        # and the attached keystream decrypts correctly
        assert eng.open_publish(t, ("pub",), wire, jobs[1]) == b"x" * 40

    def test_keyless_and_malformed_jobs(self):
        reg = KeyRegistry()
        eng = RecryptEngine(reg)
        plane = TenantPlane()
        t = plane.register("t", encrypted=("e/",))
        job = eng.decrypt_job(t, ("nobody", ""), b"\x00" * 64)
        assert job.error == "no_key" and eng.no_key_drops == 1
        reg.set_key("t", "pub", KEY_A)
        job = eng.decrypt_job(t, ("pub",), b"short")
        assert job.error == "malformed" and eng.malformed == 1
        assert eng.open_publish(t, ("pub",), b"short") is None


# -- broker-level helpers ----------------------------------------------------


async def _connect_many(h, cids, version=4):
    out = {}
    for cid in cids:
        r, w, _t = await h.connect(client_id=cid, version=version)
        out[cid] = (r, w)
    return out


async def _drain_payloads(reader, n_expected=None, idle_s=0.25):
    """Read PUBLISH frames until the stream idles; returns
    [(topic, payload)]. ``n_expected`` stops early once reached."""
    got = []
    while True:
        try:
            pk = await asyncio.wait_for(read_wire_packet(reader), idle_s)
        except asyncio.TimeoutError:
            return got
        if pk.fixed_header.type == pkts.PUBLISH:
            got.append((pk.topic_name, bytes(pk.payload)))
            if n_expected is not None and len(got) >= n_expected:
                return got


class TestStructuralIsolation:
    def test_identical_filters_zero_cross_tenant_deliveries(self):
        """The acceptance property: tenants acme and bulkco (and an
        untenanted bystander) subscribe IDENTICAL filter strings —
        exact, +, #, $SHARE, predicated — and every publish lands only
        inside its own namespace. Seeded, multi-round."""

        async def scenario():
            h = Harness(tenant_options())
            subs = {}
            try:
                conns = await _connect_many(
                    h, ["cidA", "cidA2", "cidB", "cidB2", "cidU"]
                )
                filters = [
                    "s/1/t",
                    "s/+/t",
                    "top/#",
                    "$SHARE/grp/s/#",
                    "alerts/#$CONTAINS{alarm}",
                ]
                for cid, (r, w) in conns.items():
                    w.write(
                        sub_packet(
                            1,
                            [Subscription(filter=f, qos=0) for f in filters],
                        )
                    )
                    await w.drain()
                    ack = await read_wire_packet(r)
                    assert ack.fixed_header.type == pkts.SUBACK
                rng = random.Random(12)
                topics = ["s/1/t", "s/9/t", "top/x/y", "alerts/fire"]
                sent = []  # (publisher cid, topic, payload)
                for i in range(24):
                    pub_cid = rng.choice(["cidA", "cidB", "cidU"])
                    topic = rng.choice(topics)
                    payload = f"alarm {pub_cid} {topic} {i}".encode()
                    _r, w = conns[pub_cid]
                    w.write(pub_packet(topic, payload))
                    await w.drain()
                    sent.append((pub_cid, topic, payload))
                await asyncio.sleep(0.3)
                tenant_of = {
                    "cidA": "acme",
                    "cidA2": "acme",
                    "cidB": "bulkco",
                    "cidB2": "bulkco",
                    "cidU": "",
                }
                for cid, (r, _w) in conns.items():
                    got = await _drain_payloads(r)
                    for topic, payload in got:
                        assert not topic.startswith(NS_CHAR), (
                            "scope prefix leaked to the wire"
                        )
                        pub_cid = payload.split()[1].decode()
                        assert tenant_of[pub_cid] == tenant_of[cid], (
                            f"CROSS-TENANT LEAK: {cid} got {payload!r}"
                        )
                    subs[cid] = got
                # every subscriber saw its own tenant's traffic at all
                # (the test must not pass vacuously)
                for cid in ("cidA", "cidB", "cidU"):
                    assert subs[cid], f"{cid} received nothing"
            finally:
                await h.shutdown()

        run(scenario())

    def test_retained_and_share_groups_stay_per_tenant(self):
        async def scenario():
            h = Harness(tenant_options())
            try:
                conns = await _connect_many(h, ["cidA", "cidB"])
                # same retained topic string in both tenants
                for cid, val in (("cidA", b"ra"), ("cidB", b"rb")):
                    _r, w = conns[cid]
                    w.write(pub_packet("cfg/x", val, retain=True))
                    await w.drain()
                await asyncio.sleep(0.2)
                # fresh same-tenant subscribers see only their own copy
                fresh = await _connect_many(h, ["cidA2", "cidB2"])
                for cid, want in (("cidA2", b"ra"), ("cidB2", b"rb")):
                    r, w = fresh[cid]
                    w.write(
                        sub_packet(2, [Subscription(filter="cfg/#", qos=0)])
                    )
                    await w.drain()
                    await read_wire_packet(r)  # SUBACK
                    got = await _drain_payloads(r, n_expected=1)
                    assert got == [("cfg/x", want)], (cid, got)
            finally:
                await h.shutdown()

        run(scenario())

    def test_thousand_registered_tenants_resolution_and_isolation(self):
        """1k registered tenants (the acceptance scale): resolution
        stays correct and two of them exchanging identical topics leak
        nothing — idle tenants cost the scrape nothing (no labeled
        families registered before a first CONNECT)."""

        async def scenario():
            tenants = {f"t{i:04d}": {} for i in range(1000)}
            users = {"cidA": "t0007", "cidB": "t0991"}
            h = Harness(
                tenant_options(tenants=tenants, tenant_users=users)
            )
            try:
                assert len(h.server._tenancy) == 1000
                conns = await _connect_many(h, ["cidA", "cidB"])
                for cid in conns:
                    r, w = conns[cid]
                    w.write(
                        sub_packet(1, [Subscription(filter="#", qos=0)])
                    )
                    await w.drain()
                    await read_wire_packet(r)
                for cid in conns:
                    _r, w = conns[cid]
                    w.write(pub_packet("d/x", cid.encode()))
                    await w.drain()
                await asyncio.sleep(0.25)
                for cid, (r, _w) in conns.items():
                    got = await _drain_payloads(r)
                    assert [p for _t, p in got] == [cid.encode()], (cid, got)
                # only ACTIVE tenants registered metric families
                if h.server.telemetry is not None:
                    expo = h.server.telemetry.registry.exposition()
                    assert 'tenant="t0007"' in expo
                    assert expo.count('mqtt_tpu_tenant_connected{') == 2
            finally:
                await h.shutdown()

        run(scenario())

    def test_predicated_subscriptions_scoped_per_tenant(self):
        """The same predicated filter in two tenants gates on payload
        within each namespace; the predicate engine is shared, the
        namespaces are not."""

        async def scenario():
            h = Harness(tenant_options())
            try:
                conns = await _connect_many(h, ["cidA", "cidB"])
                for cid in conns:
                    r, w = conns[cid]
                    w.write(
                        sub_packet(
                            1,
                            [
                                Subscription(
                                    filter="sens/+/v$GT{val:10}", qos=0
                                )
                            ],
                        )
                    )
                    await w.drain()
                    await read_wire_packet(r)
                for cid, val in (("cidA", 20), ("cidB", 5)):
                    _r, w = conns[cid]
                    w.write(
                        pub_packet("sens/1/v", b'{"val": %d}' % val)
                    )
                    await w.drain()
                await asyncio.sleep(0.25)
                got_a = await _drain_payloads(conns["cidA"][0])
                got_b = await _drain_payloads(conns["cidB"][0])
                # A's 20 passes its own predicate; B's 5 fails ITS OWN
                # (and neither sees the other's publish at all)
                assert got_a == [("sens/1/v", b'{"val": 20}')]
                assert got_b == []
            finally:
                await h.shutdown()

        run(scenario())

    def test_tenant_sys_scoping(self):
        """A tenant subscribing $SYS/broker/tenant/# sees ONLY its own
        counters; the untenanted operator view mirrors every active
        tenant under $SYS/broker/tenants/<name>/#."""

        async def scenario():
            h = Harness(tenant_options(sys_topic_resend_interval=1))
            try:
                conns = await _connect_many(h, ["cidA", "cidB", "cidU"])
                ra, wa = conns["cidA"]
                wa.write(
                    sub_packet(
                        1,
                        [
                            Subscription(
                                filter="$SYS/broker/tenant/#", qos=0
                            ),
                            Subscription(filter="#", qos=0),
                        ],
                    )
                )
                await wa.drain()
                await read_wire_packet(ra)
                ru, wu = conns["cidU"]
                wu.write(
                    sub_packet(
                        1,
                        [
                            Subscription(
                                filter="$SYS/broker/tenants/#", qos=0
                            )
                        ],
                    )
                )
                await wu.drain()
                await read_wire_packet(ru)
                # traffic from B so bulkco has counters too
                _rb, wb = conns["cidB"]
                wb.write(pub_packet("x/y", b"b"))
                await wb.drain()
                h.server.publish_sys_topics()
                got_a = await _drain_payloads(ra)
                assert got_a, "tenant $SYS tick delivered nothing"
                for topic, _p in got_a:
                    # ONLY the tenant-local $SYS tree — and the plain
                    # `#` subscription must NOT have matched it
                    # (the in-namespace $-rule)
                    assert topic.startswith("$SYS/broker/tenant/"), topic
                counts_a = dict(got_a)
                assert counts_a["$SYS/broker/tenant/connected"] == b"1"
                got_u = await _drain_payloads(ru)
                names = {t.split("/")[3] for t, _p in got_u}
                assert {"acme", "bulkco"} <= names
            finally:
                await h.shutdown()

        run(scenario())


class TestQuotaClasses:
    def test_vip_tenant_publishes_through_a_storm_bulk_sheds(self):
        """Quota classes measurably shape shedding (acceptance): under
        a forced SHED, the vip tenant's weighted budget absorbs the
        whole burst (zero sheds) while the bulk tenant sheds."""

        async def scenario():
            h = Harness(
                tenant_options(
                    overload_priority_classes={"vip": 100.0, "bulk": 0.01},
                    overload_shed_quota=10,
                    overload_quota_window_ms=60000.0,
                )
            )
            try:
                gov = h.server.overload
                gov.add_source("test_storm", lambda: 1.0)
                gov.evaluate(force=True)
                assert gov.state == "shed"
                conns = await _connect_many(h, ["cidA", "cidB"])
                for cid in conns:
                    _r, w = conns[cid]
                    for i in range(30):
                        w.write(pub_packet("d/x", b"p%d" % i, qos=1, pid=i + 1))
                    await w.drain()
                await asyncio.sleep(0.4)
                acme = h.server._tenancy.get("acme")
                bulk = h.server._tenancy.get("bulkco")
                assert acme.messages_dropped == 0, acme.sys_rows()
                assert bulk.messages_dropped > 0, bulk.sys_rows()
                assert acme.messages_in == 30
            finally:
                await h.shutdown()

        run(scenario())


class TestRecryptEndToEnd:
    OPTS = dict(
        tenants={
            "acme": {
                "encrypted": ["secure/"],
                "keys": {
                    "cidA": KEY_A.hex(),
                    "cidA2": KEY_S.hex(),
                },
            },
            "bulkco": {},
        },
    )

    def test_encrypted_fanout_rekeys_per_subscriber(self):
        async def scenario():
            h = Harness(tenant_options(**self.OPTS))
            try:
                eng = h.server._recrypt
                conns = await _connect_many(
                    h, ["cidA", "cidA2", "cidB", "cidB2"]
                )
                # cidA2 (keyed) and cidB/cidB2 (other tenant) subscribe
                for cid in ("cidA2", "cidB", "cidB2"):
                    r, w = conns[cid]
                    w.write(
                        sub_packet(
                            1, [Subscription(filter="secure/#", qos=0)]
                        )
                    )
                    await w.drain()
                    await read_wire_packet(r)
                plaintext = b"the plans for the fusion plant"
                wire = eng.seal_with_key(KEY_A, plaintext)
                _r, wa = conns["cidA"]
                wa.write(pub_packet("secure/plans", wire))
                await wa.drain()
                got = await _drain_payloads(conns["cidA2"][0], n_expected=1)
                assert len(got) == 1
                topic, payload = got[0]
                assert topic == "secure/plans"
                # re-keyed: decrypts under the SUBSCRIBER's key, bytes
                # differ from the publisher's ciphertext
                assert payload != wire
                assert eng.open_with_key(KEY_S, payload) == plaintext
                # nothing crossed the tenant boundary
                assert await _drain_payloads(conns["cidB"][0]) == []
                assert await _drain_payloads(conns["cidB2"][0]) == []
                assert eng.fanouts >= 1 and eng.oracle_mismatches == 0
            finally:
                await h.shutdown()

        run(scenario())

    def test_acl_denied_keyed_subscriber_is_withheld(self):
        """Regression (ISSUE 13 review): the batched encrypted fan-out
        must enforce the per-target read ACL like every other delivery
        path — a KEYED subscriber the ACL denies receives nothing, a
        keyed+allowed one still gets its re-keyed copy."""
        from mqtt_tpu.hooks import ON_ACL_CHECK, ON_CONNECT_AUTHENTICATE, Hook
        from mqtt_tpu.tenancy import local_client_id

        class DenyA2Reads(Hook):
            def id(self):
                return "deny-a2"

            def provides(self, b):
                return b in (ON_ACL_CHECK, ON_CONNECT_AUTHENTICATE)

            def on_connect_authenticate(self, cl, pk):
                return True

            def on_acl_check(self, cl, topic, write):
                return write or local_client_id(cl.id) != "cidA2"

        async def scenario():
            opts = tenant_options(
                tenants={
                    "acme": {
                        "encrypted": ["secure/"],
                        "keys": {
                            "cidA": KEY_A.hex(),
                            "cidA2": KEY_S.hex(),
                            "cidA4": KEY_S.hex(),
                        },
                    },
                    "bulkco": {},
                },
                tenant_users={
                    "cidA": "acme", "cidA2": "acme", "cidA4": "acme",
                },
            )
            h = Harness(opts, allow=False)
            h.server.add_hook(DenyA2Reads())
            try:
                eng = h.server._recrypt
                conns = await _connect_many(h, ["cidA", "cidA2", "cidA4"])
                for cid in ("cidA2", "cidA4"):
                    r, w = conns[cid]
                    w.write(
                        sub_packet(
                            1, [Subscription(filter="secure/#", qos=0)]
                        )
                    )
                    await w.drain()
                    await read_wire_packet(r)
                plaintext = b"need to know only"
                wire = eng.seal_with_key(KEY_A, plaintext)
                _r, wa = conns["cidA"]
                wa.write(pub_packet("secure/ops", wire))
                await wa.drain()
                got = await _drain_payloads(conns["cidA4"][0], n_expected=1)
                assert len(got) == 1
                assert eng.open_with_key(KEY_S, got[0][1]) == plaintext
                # the denied subscriber holds a valid key and a live
                # subscription — the ACL alone withholds delivery
                assert await _drain_payloads(conns["cidA2"][0]) == []
            finally:
                await h.shutdown()

        run(scenario())

    def test_keyless_subscriber_withheld_and_retained_rekeyed(self):
        async def scenario():
            h = Harness(tenant_options(**self.OPTS))
            try:
                eng = h.server._recrypt
                conns = await _connect_many(h, ["cidA", "cidA3"])
                # cidA3 resolves to acme via... not mapped: map it
                # through the default path instead — use an explicitly
                # mapped but KEYLESS member
                plaintext = b"retained secret"
                wire = eng.seal_with_key(KEY_A, plaintext)
                _r, wa = conns["cidA"]
                wa.write(pub_packet("secure/cfg", wire, retain=True))
                await wa.drain()
                await asyncio.sleep(0.2)
                # keyed subscriber arriving later gets the RETAINED
                # message re-keyed to it
                fresh = await _connect_many(h, ["cidA2"])
                r2, w2 = fresh["cidA2"]
                w2.write(
                    sub_packet(1, [Subscription(filter="secure/#", qos=0)])
                )
                await w2.drain()
                await read_wire_packet(r2)
                got = await _drain_payloads(r2, n_expected=1)
                assert len(got) == 1
                assert eng.open_with_key(KEY_S, got[0][1]) == plaintext
                drops_before = eng.no_key_drops
                # a keyless same-tenant subscriber receives NOTHING
                h.server._tenancy.map_user("cidA9", "acme")
                keyless = await _connect_many(h, ["cidA9"])
                r9, w9 = keyless["cidA9"]
                w9.write(
                    sub_packet(1, [Subscription(filter="secure/#", qos=0)])
                )
                await w9.drain()
                await read_wire_packet(r9)
                assert await _drain_payloads(r9) == []
                assert eng.no_key_drops > drops_before
            finally:
                await h.shutdown()

        run(scenario())

    def test_malformed_ciphertext_drops_counted(self):
        async def scenario():
            h = Harness(tenant_options(**self.OPTS))
            try:
                eng = h.server._recrypt
                conns = await _connect_many(h, ["cidA", "cidA2"])
                r2, w2 = conns["cidA2"]
                w2.write(
                    sub_packet(1, [Subscription(filter="secure/#", qos=0)])
                )
                await w2.drain()
                await read_wire_packet(r2)
                _r, wa = conns["cidA"]
                wa.write(pub_packet("secure/x", b"tiny"))  # < nonce size
                await wa.drain()
                assert await _drain_payloads(r2) == []
                assert eng.malformed >= 1
            finally:
                await h.shutdown()

        run(scenario())


class TestRecryptChaos:
    def test_device_fault_storm_degrades_to_host_everything_delivered(self):
        """The chaos leg (acceptance): a seeded device keystream fault
        storm trips the breaker to the host path — with EVERY publish
        still delivered and decrypting correctly — and the flight
        recorder dumps on trip."""

        async def scenario():
            h = Harness(
                tenant_options(
                    recrypt_device_min_blocks=1, **TestRecryptEndToEnd.OPTS
                )
            )
            try:
                eng = h.server._recrypt
                dumps = []
                if h.server.telemetry is not None:
                    orig_dump = h.server.telemetry.trigger_dump
                    h.server.telemetry.trigger_dump = (
                        lambda kind, extra=None: dumps.append((kind, extra))
                    )
                import mqtt_tpu.tenancy as tmod

                orig_async = tmod.RecryptEngine.seal_fanout
                # seed a fault window: the device dispatch path raises
                # until the breaker opens
                import mqtt_tpu.ops.recrypt as rmod

                real_ks = rmod.keystream_async
                fault = {"n": 0}

                def faulty(*a, **kw):
                    fault["n"] += 1
                    raise RuntimeError("injected keystream fault")

                rmod.keystream_async = faulty
                try:
                    conns = await _connect_many(h, ["cidA", "cidA2"])
                    r2, w2 = conns["cidA2"]
                    w2.write(
                        sub_packet(
                            1, [Subscription(filter="secure/#", qos=0)]
                        )
                    )
                    await w2.drain()
                    await read_wire_packet(r2)
                    _r, wa = conns["cidA"]
                    sent = []
                    for i in range(12):
                        plaintext = b"storm payload %d" % i
                        wire = eng.seal_with_key(KEY_A, plaintext)
                        wa.write(pub_packet("secure/s", wire))
                        sent.append(plaintext)
                    await wa.drain()
                    got = await _drain_payloads(
                        r2, n_expected=len(sent), idle_s=0.6
                    )
                    # EVERY publish delivered via the host path, in order
                    assert [
                        eng.open_with_key(KEY_S, p) for _t, p in got
                    ] == sent
                    assert eng.breaker.state == "open"
                    assert fault["n"] >= 1
                    assert eng.device_errors >= 1
                    assert ("breaker_trip", {"trigger": "recrypt_breaker"}) in dumps
                finally:
                    rmod.keystream_async = real_ks
                    assert orig_async is tmod.RecryptEngine.seal_fanout
            finally:
                await h.shutdown()

        run(scenario())


class TestStagedBroker:
    def test_staged_pipeline_carries_decrypt_jobs(self):
        """With the device matcher + staging loop on, the publisher
        decrypt keystream rides the staged batch (RecryptJob through
        MatchStage) and fan-out still re-keys correctly."""

        async def scenario():
            h = Harness(
                tenant_options(
                    device_matcher=True,
                    matcher_opts={"max_levels": 4, "background": False},
                    matcher_stage_window_ms=5.0,
                    recrypt_device_min_blocks=1,
                    **TestRecryptEndToEnd.OPTS,
                )
            )
            try:
                await h.server.serve()
                eng = h.server._recrypt
                conns = await _connect_many(h, ["cidA", "cidA2"])
                r2, w2 = conns["cidA2"]
                w2.write(
                    sub_packet(1, [Subscription(filter="secure/#", qos=0)])
                )
                await w2.drain()
                await read_wire_packet(r2)
                _r, wa = conns["cidA"]
                sent = []
                for i in range(8):
                    plaintext = b"staged %d" % i
                    wire = eng.seal_with_key(KEY_A, plaintext)
                    wa.write(pub_packet("secure/st", wire))
                    sent.append(plaintext)
                await wa.drain()
                got = await _drain_payloads(
                    r2, n_expected=len(sent), idle_s=0.8
                )
                assert [
                    eng.open_with_key(KEY_S, p) for _t, p in got
                ] == sent
                assert eng.oracle_mismatches == 0
            finally:
                await h.shutdown()
                await h.server.close()

        run(scenario())


class TestReviewRegressions:
    """Review-caught seams: tree-mode re-forward routing of scoped
    topics, per-user priority overrides under scoped registry ids, the
    username rider on encrypted forwards, and the widened nonce base."""

    def test_reforward_routes_on_the_rescoped_topic(self, tmp_path):
        """An intermediate tree hop must probe edge summaries with the
        namespace-SCOPED key (summaries hold scoped filter prefixes);
        routing on the frame's local topic would filter every tenant
        publish out at hop 2+."""
        from mqtt_tpu.cluster import Cluster
        from mqtt_tpu.mesh_topology import Topology

        class FakeServer:
            pass

        srv = FakeServer()
        from mqtt_tpu.topics import TopicsIndex

        srv.topics = TopicsIndex()
        c = Cluster(srv, 0, 3, str(tmp_path))
        c.topo = Topology(0, range(3), 2, boot_id=1)
        seen = []
        c._route_edges = lambda topic, peer, always, payload=None: (
            seen.append(topic),
            [],
        )[1]
        c._epoch_current = lambda rt: True
        pk = Packet(
            fixed_header=FixedHeader(type=pkts.PUBLISH),
            protocol_version=5,
            topic_name="e/x",
            payload=b"p",
            packet_id=1,
        )
        body = bytearray()
        pk.publish_encode(body)
        frame = bytes(body)
        c._reforward_packet(
            1, {"ns": "acme", "qos": 0}, {}, b"payload", frame
        )
        assert seen == [ns_scope_topic("acme", "e/x")]
        # and a GLOBAL frame stays unscoped
        c._reforward_packet(1, {"qos": 0}, {}, b"payload", frame)
        assert seen[1] == "e/x"

    def test_priority_user_override_sees_local_client_id(self):
        """overload_priority_users keyed on the CLIENT-SENT id must
        still override the tenant-wide quota class after the registry
        id was scoped."""
        h = Harness(
            tenant_options(
                overload_priority_classes={"vip": 4.0, "bulk": 0.5},
                overload_priority_users={"cidA": "vip"},
            )
        )
        s = h.server
        cl = s.new_client(None, None, "t", "cidA", False)
        s._resolve_tenant(cl)  # tenant acme (quota_class vip... use bulk)
        # tenant class applied first, per-user override wins after
        s._assign_priority_class(cl)
        assert cl.id.startswith(NS_CHAR)
        assert cl.priority_class == "vip" and cl.priority_weight == 4.0

    def test_origin_username_rider_resolves_remote_publisher_key(self):
        """A username-keyed publisher's key must resolve from the
        cluster head rider when the publishing session does not exist
        on this worker."""
        h = Harness(
            tenant_options(
                tenants={
                    "acme": {
                        "encrypted": ["e/"],
                        "keys": {"alice": KEY_A.hex(), "cidA2": KEY_S.hex()},
                    }
                },
            )
        )
        s = h.server
        pk = Packet(
            fixed_header=FixedHeader(type=pkts.PUBLISH),
            topic_name=ns_scope_topic("acme", "e/t"),
            payload=b"x" * 20,
            origin=scope_client_id("acme", "dev-gone"),
        )
        # without the rider: no key (session absent, id-keyed lookup misses)
        assert s._origin_idents(pk) == ("dev-gone", "")
        setattr(pk, "_origin_user", "alice")
        idents = s._origin_idents(pk)
        assert "alice" in idents
        eng = s._recrypt
        wire = eng.seal_with_key(KEY_A, b"from alice")
        tenant = s._tenancy.get("acme")
        assert eng.open_publish(tenant, idents, wire) == b"from alice"

    def test_nonce_base_is_48_bits_and_nonces_are_unique(self):
        reg = KeyRegistry()
        eng = RecryptEngine(reg)
        assert len(eng._nonce_base) == 6
        n1 = eng.next_nonce()
        batch = eng._next_nonces(64)
        assert len(n1) == 12 and batch.shape == (64, 12)
        all_nonces = {bytes(n) for n in batch} | {n1}
        assert len(all_nonces) == 65  # no collisions, counter advances
        assert all(bytes(n[:6]) == eng._nonce_base for n in batch)


class TestCrossWorker:
    def test_cross_worker_forwards_stay_per_tenant(self, tmp_path):
        """Two in-process workers: a tenant's publish forwarded across
        the mesh delivers only to the SAME tenant's subscriber on the
        other worker — and the other tenant's identical filter on that
        worker sees nothing."""
        from mqtt_tpu.cluster import Cluster

        async def scenario():
            opts0, opts1 = tenant_options(), tenant_options()
            from mqtt_tpu.hooks.auth import AllowHook

            h0, h1 = Harness(opts0), Harness(opts1)
            c0 = Cluster(h0.server, 0, 2, str(tmp_path))
            c1 = Cluster(h1.server, 1, 2, str(tmp_path))
            try:
                await c0.start()
                await c1.start()

                async def wait_for(cond, timeout=10.0):
                    deadline = asyncio.get_event_loop().time() + timeout
                    while asyncio.get_event_loop().time() < deadline:
                        if cond():
                            return True
                        await asyncio.sleep(0.02)
                    return False

                assert await wait_for(
                    lambda: c0.peer_count == 1 and c1.peer_count == 1
                )
                # subscribers on worker 1: one per tenant, same filter
                conns1 = await _connect_many(h1, ["cidA2", "cidB2"])
                for cid in conns1:
                    r, w = conns1[cid]
                    w.write(
                        sub_packet(1, [Subscription(filter="m/#", qos=1)])
                    )
                    await w.drain()
                    await read_wire_packet(r)
                # presence propagation
                assert await wait_for(
                    lambda: len(c0._remote.subscribers("\x00acme/m/x").subscriptions) > 0
                    if hasattr(c0, "_remote")
                    else True,
                    timeout=3.0,
                )
                await asyncio.sleep(0.3)
                # publisher on worker 0, tenant acme, QoS1 (packet leg)
                conns0 = await _connect_many(h0, ["cidA"])
                _r, wa = conns0["cidA"]
                wa.write(pub_packet("m/x", b"cross", qos=1, pid=7))
                await wa.drain()
                got_a = await _drain_payloads(conns1["cidA2"][0], idle_s=1.0)
                got_b = await _drain_payloads(conns1["cidB2"][0], idle_s=0.3)
                assert [
                    (t, p) for t, p in got_a if t == "m/x"
                ], f"same-tenant cross-worker delivery missing: {got_a}"
                assert got_b == [], f"CROSS-TENANT LEAK over the mesh: {got_b}"
            finally:
                await c0.stop()
                await c1.stop()
                await h0.shutdown()
                await h1.shutdown()

        run(scenario())


class TestEpochRekey:
    """The live re-key epoch machinery (ISSUE 20 tentpole): nonce
    tagging, the stage -> activate -> retire lifecycle, and the atomic
    fan-out (key ids, epoch) resolution. End-to-end (zero gaps, zero
    old-key leaks under load) lives in the ``tenant_rekey`` scenario."""

    K0 = bytes(range(16))
    K1 = bytes(range(16, 32))

    def test_nonce_tag_round_trip(self):
        from mqtt_tpu.tenancy import (
            EPOCH_NONCE_MAGIC,
            epoch_tag_nonce,
            nonce_epoch,
        )

        nonce = bytes(range(100, 112))
        tagged = epoch_tag_nonce(nonce, 3)
        assert len(tagged) == 12
        assert tagged[0] == EPOCH_NONCE_MAGIC
        assert nonce_epoch(tagged) == 3
        assert tagged[3:] == nonce[3:]  # client uniqueness bytes survive
        assert nonce_epoch(bytes(12)) is None  # untagged stays opaque
        assert nonce_epoch(epoch_tag_nonce(nonce, 0)) == 0

    def test_stage_activate_retire_lifecycle(self):
        from mqtt_tpu.tenancy import KeyRegistry

        ks = KeyRegistry()
        kid0 = ks.set_key("acme", "sub", self.K0)
        assert not ks.has_epochs("acme")
        assert ks.current_epoch("acme") == 0

        epoch = ks.stage_epoch("acme", {"sub": self.K1})
        assert epoch == 1
        assert ks.staged_epoch("acme") == 1
        assert ks.has_epochs("acme")
        # staged but NOT active: current lookups keep the old generation
        assert ks.key_id("acme", "sub") == kid0
        assert ks.current_epoch("acme") == 0

        assert ks.activate_epoch("acme") == 1
        kid1 = ks.key_id("acme", "sub")
        assert kid1 != kid0
        assert ks.current_epoch("acme") == 1
        # the drain window: both generations stay addressable by tag
        assert ks.kid_for_epoch("acme", "sub", 0) == kid0
        assert ks.kid_for_epoch("acme", "sub", 1) == kid1

        scrubbed = ks.retire_epoch("acme", 0)
        assert scrubbed == 1
        assert ks.kid_for_epoch("acme", "sub", 0) == -2  # stale
        assert ks.kid_for_epoch("acme", "sub", 1) == kid1  # live untouched
        assert not ks._round_keys[kid0].any()  # old key material zeroed

    def test_activate_without_stage_is_noop(self):
        from mqtt_tpu.tenancy import KeyRegistry

        ks = KeyRegistry()
        ks.set_key("t", "a", self.K0)
        assert ks.activate_epoch("t") == -1
        assert ks.current_epoch("t") == 0

    def test_retire_never_takes_the_live_epoch(self):
        from mqtt_tpu.tenancy import KeyRegistry

        ks = KeyRegistry()
        ks.set_key("t", "a", self.K0)
        ks.stage_epoch("t", {"a": self.K1})
        ks.activate_epoch("t")
        ks.retire_epoch("t", 1)  # asks for the CURRENT epoch
        # the floor clamps at the live generation: epoch 1 still serves
        assert ks.kid_for_epoch("t", "a", 1) >= 0
        assert ks.key_id("t", "a") >= 0

    def test_epoch0_identity_resolvable_without_explicit_record(self):
        from mqtt_tpu.tenancy import KeyRegistry

        ks = KeyRegistry()
        kid = ks.set_key("t", "a", self.K0)
        # identities keyed before any rotation live at epoch 0 via _ids
        assert ks.kid_for_epoch("t", "a", 0) == kid
        assert ks.kid_for_epoch("t", "a", 7) == -1  # unknown generation
        assert ks.kid_for_epoch("t", "ghost", 0) == -1

    def test_key_ids_with_epoch_is_atomic_per_generation(self):
        from mqtt_tpu.tenancy import KeyRegistry

        ks = KeyRegistry()
        ks.set_key("t", "a", self.K0)
        ks.set_key("t", "b", self.K1)
        ids, epoch = ks.key_ids_with_epoch("t", [("a",), ("b",), ("nope",)])
        assert epoch == 0
        assert ids[0] >= 0 and ids[1] >= 0 and ids[2] == -1
        ks.stage_epoch("t", {"a": self.K1, "b": self.K0})
        ks.activate_epoch("t")
        ids2, epoch2 = ks.key_ids_with_epoch("t", [("a",), ("b",)])
        assert epoch2 == 1
        assert set(ids2).isdisjoint(ids[:2])  # new generation, new rows
