"""Chaos/robustness suite for the broker-wide overload control plane
(mqtt_tpu.overload): the NORMAL -> THROTTLE -> SHED governor, bounded
staging admission, THROTTLE read-pausing, SHED 0x97 shedding,
slow-consumer eviction, tiered cluster forward shedding, and the seeded
publish-storm drills (mqtt_tpu.faults.StormPlan / drive_storm).

The storm acceptance drill: offered load far above sustainable, staging
pending depth and aggregate outbound backlog stay below their caps,
admitted QoS1 traffic is delivered exactly once with bounded latency,
shed publishes get v5 reason 0x97, the slow consumer is evicted with
DISCONNECT 0x97, and the governor returns to NORMAL within the
hysteresis window once the storm stops — all visible through the
$SYS/broker/overload/* gauges.
"""

import asyncio
import logging
import os
import time

import pytest

from mqtt_tpu import Options, Server
from mqtt_tpu.faults import FaultPlan, FaultyMatcher, StormPlan, drive_storm
from mqtt_tpu.overload import (
    NORMAL,
    SHED,
    THROTTLE,
    OverloadConfig,
    OverloadGovernor,
)
from mqtt_tpu.packets import DISCONNECT, PINGREQ, PUBACK, PUBLISH, SUBACK
from mqtt_tpu.packets import FixedHeader, Packet, Subscription, encode_packet
from mqtt_tpu.staging import MatchStage
from mqtt_tpu.topics import SYS_PREFIX, Subscribers

from tests.test_server import (
    Harness,
    pub_packet,
    read_wire_packet,
    run,
    sub_packet,
)


class FakeClock:
    def __init__(self) -> None:
        self.t = 100.0

    def __call__(self) -> float:
        return self.t


def make_governor(**kw):
    clock = FakeClock()
    kw.setdefault("eval_interval_s", 0.0)
    kw.setdefault("min_dwell_s", 1.0)
    gov = OverloadGovernor(OverloadConfig(**kw), clock=clock)
    pressure = [0.0]
    gov.add_source("test", lambda: pressure[0])
    return gov, clock, pressure


class StubClient:
    def __init__(self, cid="c1"):
        self.id = cid
        self._pub_epoch = -1
        self._pub_count = 0


# -- unit: governor state machine -------------------------------------------


class TestGovernorStateMachine:
    def test_hysteresis_bands_and_dwell(self):
        gov, clock, pressure = make_governor()
        assert gov.evaluate() == NORMAL

        pressure[0] = 0.75  # above throttle_enter (0.70): escalate now
        assert gov.evaluate() == THROTTLE
        pressure[0] = 0.60  # inside the band (exit 0.50): hold
        clock.t += 5
        assert gov.evaluate() == THROTTLE

        pressure[0] = 0.95  # above shed_enter (0.90): escalate now
        assert gov.evaluate() == SHED
        pressure[0] = 0.60  # below shed_exit (0.65) but dwell not elapsed
        assert gov.evaluate() == SHED
        clock.t += 2  # dwell elapsed; 0.60 >= throttle_exit: step down one
        assert gov.evaluate() == THROTTLE

        pressure[0] = 0.10
        assert gov.evaluate() == THROTTLE  # just entered: dwell again
        clock.t += 2
        assert gov.evaluate() == NORMAL
        assert gov.transitions == 4

    def test_shed_exits_straight_to_normal_when_quiet(self):
        gov, clock, pressure = make_governor()
        pressure[0] = 1.5
        assert gov.evaluate() == SHED
        pressure[0] = 0.0
        clock.t += 2
        assert gov.evaluate() == NORMAL

    def test_escalation_ignores_dwell(self):
        gov, clock, pressure = make_governor(min_dwell_s=60.0)
        pressure[0] = 0.8
        assert gov.evaluate() == THROTTLE
        pressure[0] = 0.99  # straight up, no dwell for escalation
        assert gov.evaluate() == SHED

    def test_eval_interval_rate_limits_lazy_calls(self):
        gov, clock, pressure = make_governor(eval_interval_s=1.0)
        pressure[0] = 2.0
        gov.evaluate(force=True)
        assert gov.state == SHED
        pressure[0] = 0.0
        clock.t += 10
        e0 = gov.epoch
        gov.evaluate()  # interval elapsed: runs, window rolled
        e1 = gov.epoch
        assert e1 != e0
        gov.evaluate()  # within the interval: no-op
        assert gov.epoch == e1

    def test_failing_source_reads_as_zero(self):
        gov, clock, pressure = make_governor()

        def boom():
            raise RuntimeError("signal died")

        gov.add_source("bad", boom)
        pressure[0] = 0.2
        assert gov.evaluate() == NORMAL
        assert gov.signal_pressures["bad"] == 0.0

    def test_admit_quota_per_window(self):
        gov, clock, pressure = make_governor(
            shed_quota=2, eval_interval_s=1000.0, quota_window_s=10.0
        )
        cl = StubClient()
        pressure[0] = 2.0
        gov.evaluate(force=True)
        assert gov.state == SHED
        assert gov.admit(cl) and gov.admit(cl)
        assert not gov.admit(cl)  # third in the window sheds
        assert gov.sheds == 1
        # sampling again within the same wall-clock window must NOT
        # refill the budget
        gov.evaluate(force=True)
        assert not gov.admit(cl)
        clock.t += 10  # the window rolls on the clock
        gov.evaluate(force=True)
        assert gov.admit(cl)
        # another client has its own budget
        assert gov.admit(StubClient("c2"))

    def test_admit_always_true_outside_shed(self):
        gov, clock, pressure = make_governor(shed_quota=1)
        cl = StubClient()
        for _ in range(10):
            assert gov.admit(cl)
        assert gov.sheds == 0

    def test_read_delay_only_for_over_quota_publishers(self):
        gov, clock, pressure = make_governor(
            publish_quota=5, throttle_delay_s=0.033, eval_interval_s=1000.0
        )
        cl = StubClient()
        pressure[0] = 0.8
        gov.evaluate(force=True)
        assert gov.state == THROTTLE
        assert gov.read_delay(cl) == 0.0  # first call syncs the window
        cl._pub_count = 3
        assert gov.read_delay(cl) == 0.0  # under quota
        cl._pub_count = 50
        assert gov.read_delay(cl) == pytest.approx(0.033)
        assert gov.throttled == 1
        pressure[0] = 0.0
        clock.t += 5
        gov.evaluate(force=True)
        assert gov.read_delay(cl) == 0.0  # NORMAL again

    def test_evict_due_requires_shed_and_grace(self):
        gov, clock, pressure = make_governor(eviction_grace_s=2.0)
        t0 = clock.t
        clock.t += 5
        assert not gov.evict_due(t0)  # NORMAL: never
        pressure[0] = 2.0
        gov.evaluate(force=True)
        assert gov.evict_due(t0)  # SHED + grace expired
        assert not gov.evict_due(clock.t - 0.5)  # within grace
        assert not gov.evict_due(None)

    def test_qos0_forward_fraction_tiers(self):
        gov, clock, pressure = make_governor(
            qos0_forward_throttle_fraction=0.5,
            qos0_forward_shed_fraction=0.25,
        )
        assert gov.qos0_forward_fraction() == 1.0
        pressure[0] = 0.8
        gov.evaluate(force=True)
        assert gov.qos0_forward_fraction() == 0.5
        pressure[0] = 2.0
        gov.evaluate(force=True)
        assert gov.qos0_forward_fraction() == 0.25

    def test_gauges_shape(self):
        gov, clock, pressure = make_governor()
        pressure[0] = 0.95
        gov.evaluate(force=True)
        g = gov.gauges()
        assert g["state"] == SHED and g["state_code"] == 2
        assert g["pressure"] == pytest.approx(0.95)
        assert g["signal/test"] == pytest.approx(0.95)
        assert g["peak/test"] == pytest.approx(0.95)
        for key in ("sheds", "evictions", "throttled", "transitions"):
            assert key in g


class TestOptionNormalization:
    def test_inverted_bands_and_zero_caps_are_repaired(self):
        o = Options(
            overload_throttle_enter=0.5,
            overload_throttle_exit=0.9,  # inverted
            overload_shed_enter=0.3,  # below throttle_enter
            overload_shed_exit=0.8,  # inverted
            overload_stage_max_pending=0,
            overload_max_outbound_backlog=-5,
            overload_eval_interval_ms=0,
            overload_publish_quota=0,
            overload_shed_quota=-1,
        )
        o.ensure_defaults()
        assert o.overload_throttle_exit <= o.overload_throttle_enter
        assert o.overload_shed_exit <= o.overload_shed_enter
        assert o.overload_shed_enter >= o.overload_throttle_enter
        assert o.overload_stage_max_pending > 0
        assert o.overload_max_outbound_backlog > 0
        assert o.overload_eval_interval_ms > 0
        assert o.overload_publish_quota > 0
        assert o.overload_shed_quota > 0


# -- unit: bounded staging admission ----------------------------------------


class TestBoundedStagingAdmission:
    def test_overflow_resolves_via_host_walk(self):
        async def scenario():
            hits = []

            def host(topic):
                hits.append(topic)
                return Subscribers()

            stage = MatchStage(None, host, max_pending=3)
            # arm submission without starting the collector, so parked
            # entries stay parked and the bound is observable
            stage._wake = asyncio.Event()
            parked = [stage.submit(f"t/{i}") for i in range(3)]
            assert all(not f.done() for f in parked)
            over = stage.submit("t/over")
            assert over.done()  # resolved NOW via the host walk
            assert hits == ["t/over"]
            assert stage.admission_fallbacks == 1
            assert stage.peak_pending == 3
            assert stage.pending_depth == 3
            assert stage.pressure() == pytest.approx(1.0)
            await stage.stop()  # drains the parked entries via host walk
            assert all(f.done() for f in parked)

        run(scenario())

    def test_deadline_aware_admission(self):
        async def scenario():
            stage = MatchStage(
                None,
                lambda t: Subscribers(),
                latency_budget_s=0.1,
                max_pending=1000,
            )
            stage._wake = asyncio.Event()
            stage._queue = asyncio.Queue(maxsize=8)
            stage._ewma_s = 0.05
            # depth 1 (no queue backlog): projected 0.05 < 0.2 deadline
            f1 = stage.submit("a")
            assert not f1.done()
            for _ in range(4):
                stage._queue.put_nowait(None)
            # projected wait (1 + 4) * 0.05 = 0.25 > 2 x 0.1: host walk
            f2 = stage.submit("b")
            assert f2.done()
            assert stage.admission_fallbacks == 1
            stage._queue = None
            await stage.stop()

        run(scenario())

    def test_no_adaptation_means_no_deadline(self):
        async def scenario():
            stage = MatchStage(
                None, lambda t: Subscribers(), latency_budget_s=None,
                max_pending=10,
            )
            stage._wake = asyncio.Event()
            stage._ewma_s = 99.0
            assert not stage._past_deadline()
            f = stage.submit("x")
            assert not f.done()
            await stage.stop()

        run(scenario())


# -- unit: tiered cluster forward shedding ----------------------------------


class _FakeTransport:
    def __init__(self, buffered: int) -> None:
        self.buffered = buffered
        self.aborted = False

    def get_write_buffer_size(self) -> int:
        return self.buffered

    def abort(self) -> None:
        self.aborted = True


class _FakeWriter:
    def __init__(self, buffered: int) -> None:
        self.transport = _FakeTransport(buffered)
        self.sent = []

    def write(self, data: bytes) -> None:
        self.sent.append(data)


class TestClusterTieredShedding:
    def _cluster(self, tmp_path):
        from mqtt_tpu.cluster import Cluster
        from mqtt_tpu.topics import TopicsIndex

        class FakeServer:
            pass

        srv = FakeServer()
        srv.topics = TopicsIndex()
        gov, clock, pressure = make_governor()
        srv.overload = gov
        c = Cluster(srv, 0, 2, str(tmp_path))
        return c, gov, pressure

    def test_qos0_sheds_at_reduced_cap_while_shedding(self, tmp_path):
        from mqtt_tpu.cluster import _T_FRAME, _T_PACKET, Cluster

        c, gov, pressure = self._cluster(tmp_path)
        # 40% of the buffer used: fine in NORMAL, over the 25% SHED tier
        w = _FakeWriter(int(0.4 * Cluster.MAX_PEER_BUFFER))
        assert c._send_nowait(1, w, _T_FRAME, b"f", qos=0)
        pressure[0] = 2.0
        gov.evaluate(force=True)
        assert not c._send_nowait(1, w, _T_FRAME, b"f", qos=0)
        assert c.shed_qos0_forwards == 1
        assert c.dropped_forwards == 1
        assert gov.sheds == 1
        # QoS>0 keeps the FULL cap: same buffer passes
        assert c._send_nowait(1, w, _T_PACKET, b"p", qos=1)
        # ...until the full cap, where it drops but is NOT a shed
        w2 = _FakeWriter(Cluster.MAX_PEER_BUFFER + 1)
        assert not c._send_nowait(1, w2, _T_PACKET, b"p", qos=1)
        assert c.shed_qos0_forwards == 1  # unchanged

    def test_control_traffic_never_sheds(self, tmp_path):
        from mqtt_tpu.cluster import _T_PRESENCE, Cluster

        c, gov, pressure = self._cluster(tmp_path)
        pressure[0] = 2.0
        gov.evaluate(force=True)
        w = _FakeWriter(int(2 * Cluster.MAX_PEER_BUFFER))
        assert c._send_nowait(1, w, _T_PRESENCE, b"s")  # over every tier
        assert w.sent
        # only a wedged link (8x) closes it
        w3 = _FakeWriter(9 * Cluster.MAX_PEER_BUFFER)
        assert not c._send_nowait(1, w3, _T_PRESENCE, b"s")
        assert w3.transport.aborted

    def test_buffer_pressure_signal(self, tmp_path):
        from mqtt_tpu.cluster import Cluster

        c, gov, pressure = self._cluster(tmp_path)
        assert c._buffer_pressure() == 0.0
        c._writers[1] = _FakeWriter(Cluster.MAX_PEER_BUFFER // 2)
        c._writers[2] = _FakeWriter(Cluster.MAX_PEER_BUFFER // 4)
        assert c._buffer_pressure() == pytest.approx(0.5)


# -- e2e helpers -------------------------------------------------------------


def storm_options(**kw):
    return Options(
        inline_client=True,
        device_matcher=True,
        matcher_stage_window_ms=1.0,
        matcher_opts={"max_levels": 4, "background": False},
        overload_stage_max_pending=kw.pop("max_pending", 32),
        overload_throttle_enter=kw.pop("throttle_enter", 0.30),
        overload_throttle_exit=kw.pop("throttle_exit", 0.10),
        overload_shed_enter=kw.pop("shed_enter", 0.45),
        overload_shed_exit=kw.pop("shed_exit", 0.20),
        overload_eval_interval_ms=kw.pop("eval_ms", 30.0),
        overload_min_dwell_ms=kw.pop("dwell_ms", 100.0),
        overload_publish_quota=kw.pop("publish_quota", 100_000),
        overload_shed_quota=kw.pop("shed_quota", 5),
        overload_eviction_grace_ms=kw.pop("grace_ms", 200.0),
        **kw,
    )


async def collect_acks(reader, want: int, out: dict) -> None:
    """Read ``want`` PUBACKs off one v5 publisher stream into
    ``out[packet_id] = (reason_code, arrival_time)``."""
    got = 0
    while got < want:
        pk = await asyncio.wait_for(read_wire_packet(reader, 5), 10)
        if pk.fixed_header.type == PUBACK:
            out[pk.packet_id] = (pk.reason_code, time.perf_counter())
            got += 1


def qos1_tags(schedule):
    """payload tag (s<p>-<m>) per QoS1 message, in packet-id order."""
    return [p.split(b"|", 1)[0] for (_s, _t, p, q) in schedule if q]


class DeliveryCollector:
    """Reads the healthy subscriber CONCURRENTLY with the storm (it must
    keep draining, or its own transport backlog would make it a slow
    consumer); records delivered payload tags and first-arrival times."""

    def __init__(self, reader) -> None:
        self.got: list = []
        self.seen_at: dict = {}
        self._done = asyncio.Event()
        self._task = asyncio.ensure_future(self._run(reader))

    async def _run(self, reader) -> None:
        while True:
            try:
                pk = await asyncio.wait_for(read_wire_packet(reader), 0.8)
            except asyncio.TimeoutError:
                if self._done.is_set():
                    return  # storm over and the stream went quiet
                continue
            if pk.fixed_header.type != PUBLISH:
                continue
            tag = bytes(pk.payload).split(b"|", 1)[0]
            self.seen_at.setdefault(tag, time.perf_counter())
            self.got.append(tag)

    async def finish(self) -> list:
        self._done.set()
        await self._task
        return self.got

    def admitted_latencies(self, admitted: set, ack_times: dict) -> list:
        """Admitted-QoS1 fan-out latency: PUBACK arrival (admission is
        decided before the ack is written) to subscriber delivery — the
        broker's own latency, free of client-side socket queueing."""
        return sorted(
            self.seen_at[tag] - ack_times[tag]
            for tag in admitted
            if tag in self.seen_at and tag in ack_times
        )


async def run_publish_storm(h, plan, slow_consumer=False, sub_filter="storm/#"):
    """Drive one seeded storm through a Harness broker: a healthy
    wildcard subscriber (drained live by a DeliveryCollector), optionally
    a never-reading slow consumer, N v5 publishers with ack collectors.
    Returns (admitted_tags, shed_tags, ack_times, collector, slow_conn)."""
    sub_r, sub_w, _ = await h.connect("sub")
    sub_w.write(sub_packet(1, [Subscription(filter=sub_filter, qos=0)]))
    await sub_w.drain()
    assert (await read_wire_packet(sub_r)).fixed_header.type == SUBACK
    slow_conn = None
    if slow_consumer:
        slow_r, slow_w, _ = await h.connect("slowpoke", version=5)
        slow_w.write(
            sub_packet(2, [Subscription(filter="storm/#", qos=0)], version=5)
        )
        await slow_w.drain()
        assert (await read_wire_packet(slow_r, 5)).fixed_header.type == SUBACK
        # shrink both kernel buffers toward their floors so the unread
        # backlog lands in the server's TRANSPORT buffer, where the
        # overload sweep's watermark can see it (AF_UNIX queues data on
        # the RECEIVER's buffer, so the victim's rcvbuf matters most)
        import socket as _socket

        srv_sock = h.server.clients.get("slowpoke").net.writer.get_extra_info(
            "socket"
        )
        if srv_sock is not None:
            srv_sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_SNDBUF, 4096)
        cli_sock = slow_w.get_extra_info("socket")
        if cli_sock is not None:
            cli_sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_RCVBUF, 4096)
        # a truly stalled consumer: its receive window stays closed, so
        # nothing drains into the client-side StreamReader either
        slow_w.transport.pause_reading()
        slow_conn = (slow_r, slow_w)
    h.server.matcher.flush()
    collector = DeliveryCollector(sub_r)

    schedules = plan.schedule()
    writers, acks, ack_tasks = [], [], []
    for p in range(plan.publishers):
        r, w, _ = await h.connect(f"storm-p{p}", version=5)
        writers.append(w)
        want = sum(1 for (_s, _t, _pl, q) in schedules[p] if q)
        out = {}
        acks.append(out)
        ack_tasks.append(asyncio.ensure_future(collect_acks(r, want, out)))

    await drive_storm(writers, plan)
    await asyncio.gather(*ack_tasks)

    admitted, shed, ack_times = set(), set(), {}
    for p in range(plan.publishers):
        tags = qos1_tags(schedules[p])
        for pid, (reason, t_ack) in acks[p].items():
            tag = tags[pid - 1]
            if reason == 0x97:
                shed.add(tag)
            else:
                admitted.add(tag)
                ack_times[tag] = t_ack
    return admitted, shed, ack_times, collector, slow_conn


async def await_normal(gov, timeout_s=6.0):
    deadline = time.monotonic() + timeout_s
    while gov.state != NORMAL and time.monotonic() < deadline:
        gov.evaluate(force=True)
        await asyncio.sleep(0.05)
    return gov.state


# -- e2e: the storm acceptance drill ----------------------------------------


class TestPublishStorm:
    def test_storm_sheds_gracefully_and_recovers(self):
        """Offered load far above what the (slowed) stage sustains:
        pending depth stays at/below its cap, some QoS1 publishes get
        0x97, every ADMITTED QoS1 publish is delivered exactly once, no
        shed one leaks, and the governor walks back to NORMAL — all
        asserted through the $SYS gauges too."""

        async def scenario():
            h = Harness(storm_options())
            # a uniformly slow device: every dispatch takes ~20ms, so the
            # storm outruns the pipeline and pressure builds (seeded,
            # replayable; slow must NOT trip the breaker)
            h.server.matcher = FaultyMatcher(
                h.server.matcher, FaultPlan(seed=5, slow_rate=1.0, slow_s=0.02)
            )
            await h.server.serve()
            gov = h.server.overload

            plan = StormPlan(
                seed=42, publishers=5, msgs_per_publisher=60,
                topic_space=8, qos1_fraction=0.5,
            )
            admitted, shed, ack_times, collector, _ = await run_publish_storm(
                h, plan
            )
            assert shed, "the storm never shed: offered load too low"
            assert admitted, "everything shed: admission collapsed"
            delivered = await collector.finish()
            lat = collector.admitted_latencies(admitted, ack_times)
            # every admitted QoS1 message exactly once, no shed leak
            from collections import Counter

            counts = Counter(delivered)
            for tag in admitted:
                assert counts[tag] == 1, (tag, counts[tag])
            for tag in shed:
                assert counts[tag] == 0, f"shed {tag} was delivered"
            # admitted-traffic fan-out p99 stays bounded (stage budget is
            # 250ms; generous CI allowance)
            if lat:
                assert lat[max(0, int(len(lat) * 0.99) - 1)] < 3.0

            # backlogs stayed within their configured caps
            stage = h.server._stage
            assert stage.peak_pending <= stage.max_pending
            peak_out = gov.peak_pressures.get("outbound", 0.0)
            assert peak_out <= 1.0
            assert gov.sheds >= len(shed)

            # the governor returns to NORMAL within the hysteresis window
            assert await await_normal(gov) == NORMAL

            # ...and the whole story is visible in $SYS
            h.server.publish_sys_topics()
            retained = h.server.topics.retained

            def gauge(name):
                pk = retained.get(SYS_PREFIX + "/broker/overload/" + name)
                return None if pk is None else pk.payload.decode()

            assert gauge("state") == NORMAL
            assert int(gauge("sheds")) >= len(shed)
            assert int(gauge("transitions")) >= 1
            assert int(gauge("stage_peak_pending")) <= stage.max_pending
            assert gauge("evictions") is not None
            assert gauge("signal/staging") is not None

            await h.server.close()
            await h.shutdown()

        run(scenario())

    def test_slow_consumer_evicted_with_0x97(self):
        """SHED posture + a consumer whose outbound queue stays full past
        the grace window => DISCONNECT 0x97 Quota Exceeded and a freed
        backlog (the eviction gauge counts it)."""

        async def scenario():
            opts = Options(
                inline_client=True,
                overload_eval_interval_ms=20.0,
                overload_eviction_grace_ms=100.0,
                overload_min_dwell_ms=50.0,
                # tiny transport-buffer watermark: a non-reading peer
                # crosses it as soon as the socket buffer is full
                overload_client_buffer_limit_bytes=4096,
            )
            h = Harness(opts)
            await h.server.serve()
            gov = h.server.overload

            slow_r, slow_w, _ = await h.connect("slowpoke", version=5)
            slow_w.write(
                sub_packet(1, [Subscription(filter="e/#", qos=0)], version=5)
            )
            await slow_w.drain()
            assert (await read_wire_packet(slow_r, 5)).fixed_header.type == SUBACK

            pub_r, pub_w, _ = await h.connect("pub")
            # ~1.3MB of fan-out the victim never reads: the socketpair
            # buffer fills and the rest parks in the transport buffer
            payload = b"x" * 32768
            for i in range(40):
                pub_w.write(pub_packet("e/x", payload))
            await pub_w.drain()
            await asyncio.sleep(0.2)
            h.server.sweep_overload()  # observes the over-limit backlog
            cl = h.server.clients.get("slowpoke")
            assert cl.state.backlog_over_since is not None

            # force SHED (the signal a real storm would provide)
            pressure = [2.0]
            gov.add_source("test", lambda: pressure[0])
            h.server.sweep_overload()
            assert gov.state == SHED
            assert gov.evictions == 0  # grace not elapsed yet
            await asyncio.sleep(0.15)  # grace (100ms) expires
            h.server.sweep_overload()

            assert gov.evictions == 1
            assert h.server.clients.get("slowpoke").closed
            # the victim sees DISCONNECT 0x97 after the queued publishes
            while True:
                pk = await asyncio.wait_for(read_wire_packet(slow_r, 5), 10)
                if pk.fixed_header.type == DISCONNECT:
                    assert pk.reason_code == 0x97
                    break

            # recovery: pressure gone, governor returns to NORMAL
            pressure[0] = 0.0
            assert await await_normal(gov) == NORMAL
            await h.server.close()
            await h.shutdown()

        run(scenario())

    def test_throttle_pauses_over_quota_publisher(self):
        """THROTTLE: a publisher past its window quota gets its reads
        paused (counted in the throttled gauge); an idle client does
        not."""

        async def scenario():
            opts = Options(
                inline_client=True,
                overload_publish_quota=5,
                overload_throttle_delay_ms=20.0,
                # freeze automatic window rolls: the test drives epochs
                overload_eval_interval_ms=60_000.0,
            )
            h = Harness(opts)
            await h.server.serve()
            gov = h.server.overload
            pressure = [0.8]
            gov.add_source("test", lambda: pressure[0])
            gov.evaluate(force=True)
            assert gov.state == THROTTLE

            pub_r, pub_w, _ = await h.connect("pub")
            # sync this client's quota window with one cheap round trip
            pub_w.write(
                encode_packet(
                    Packet(fixed_header=FixedHeader(type=PINGREQ), protocol_version=4)
                )
            )
            await pub_w.drain()
            await read_wire_packet(pub_r)

            deadline = time.monotonic() + 8
            while gov.throttled == 0 and time.monotonic() < deadline:
                pub_w.write(
                    b"".join(pub_packet("t/x", b"p") for _ in range(10))
                )
                await pub_w.drain()
                await asyncio.sleep(0.05)
            assert gov.throttled >= 1
            cl = h.server.clients.get("pub")
            assert cl._pub_count > 5

            await h.server.close()
            await h.shutdown()

        run(scenario())


# -- slow-marked: the sustained 10x storm (chaos smoke) ----------------------


@pytest.mark.slow
class TestSustainedStorm:
    def test_sustained_storm_10x(self):
        """The full acceptance drill at sustained scale: a seeded storm
        whose offered rate is >= 10x the admitted (sustainable) rate,
        with a slow consumer in the blast radius. Caps hold, admitted
        QoS1 delivery is exact, sheds carry 0x97, the slow consumer is
        evicted, and the governor recovers to NORMAL."""

        async def scenario():
            # a STICKY shed posture: the exit band sits near zero, the
            # dwell is long (NORMAL dips between pressure waves are what
            # admit excess traffic), evaluation is frequent (short dips),
            # and the batch cap is small so the pipeline cannot amortize
            # the whole blast into a handful of device batches — together
            # these keep the offered:admitted ratio >= 10x measurable
            h = Harness(
                storm_options(
                    shed_quota=1,
                    shed_enter=0.30,
                    shed_exit=0.02,
                    throttle_enter=0.15,
                    throttle_exit=0.01,
                    eval_ms=25.0,
                    dwell_ms=2000.0,
                    grace_ms=300.0,
                    overload_client_buffer_limit_bytes=8192,
                    overload_quota_window_ms=100.0,
                    matcher_stage_max_batch=64,
                )
            )
            h.server.matcher = FaultyMatcher(
                h.server.matcher, FaultPlan(seed=9, slow_rate=1.0, slow_s=0.05)
            )
            await h.server.serve()
            gov = h.server.overload
            # pin the stage to tiny batches: sustainable service is then
            # ~8 topics / 50ms = 160 msg/s, an order of magnitude under
            # the blast — the 10x-over-sustainable operating point
            stage = h.server._stage
            stage.min_batch = stage.max_batch = stage._batch_cap = 8

            msgs = int(os.environ.get("STORM_MSGS", "1500"))
            # small payloads keep the BLAST fast (big ones throttle the
            # publishers themselves below the pipeline's sustainable
            # rate, and the governor then legitimately recovers mid-run)
            plan = StormPlan(
                seed=1207, publishers=8, msgs_per_publisher=msgs,
                topic_space=16, qos1_fraction=0.5, payload_pad=64,
            )
            t0 = time.perf_counter()
            # the healthy subscriber watches ONE publisher's subtree: the
            # oracle stays exact over that slice while the subscriber
            # itself stays comfortably inside its drain budget (a sub on
            # the full 8-publisher blast would legitimately become a
            # slow consumer on this shared event loop)
            admitted, shed, ack_times, collector, slow_conn = (
                await run_publish_storm(
                    h, plan, slow_consumer=True, sub_filter="storm/p0/#"
                )
            )
            storm_s = time.perf_counter() - t0
            offered = plan.publishers * msgs
            offered_rate = offered / storm_s
            admitted_qos1 = len(admitted)
            delivered = await collector.finish()
            admitted_p0 = {t for t in admitted if t.startswith(b"s0-")}
            shed_p0 = {t for t in shed if t.startswith(b"s0-")}
            lat = collector.admitted_latencies(admitted_p0, ack_times)

            from collections import Counter

            counts = Counter(delivered)
            assert admitted_p0, "publisher 0 had nothing admitted"
            for tag in admitted_p0:
                assert counts[tag] == 1
            for tag in shed_p0:
                assert counts[tag] == 0

            # 10x: the blast offered at least 10x what was admitted
            assert offered >= 10 * admitted_qos1, (
                f"offered={offered} admitted_qos1={admitted_qos1} "
                f"rate={offered_rate:.0f}/s in {storm_s:.1f}s"
            )
            # bounded backlogs under the sustained blast
            stage = h.server._stage
            assert stage.peak_pending <= stage.max_pending
            assert gov.peak_pressures.get("outbound", 0.0) <= 1.0
            # admitted-traffic fan-out p99 stays bounded
            if lat:
                assert lat[max(0, int(len(lat) * 0.99) - 1)] < 3.0
            # the slow consumer's unread backlog (transport buffer far
            # past the watermark) costs it eviction under SHED; if the
            # storm's own sweeps didn't catch it, hold the posture long
            # enough for the grace window — the backlog is still there
            if gov.evictions == 0:
                hold = [1.0]
                gov.add_source("hold", lambda: hold[0])
                gov.evaluate(force=True)
                h.server.sweep_overload()
                await asyncio.sleep(0.35)
                h.server.sweep_overload()
                hold[0] = 0.0
            slow_r, slow_w = slow_conn
            slow_w.transport.resume_reading()  # the victim reads its fate
            saw_disconnect = False
            try:
                while True:
                    pk = await asyncio.wait_for(read_wire_packet(slow_r, 5), 3)
                    if pk.fixed_header.type == DISCONNECT:
                        saw_disconnect = pk.reason_code == 0x97
                        break
            except (asyncio.TimeoutError, asyncio.IncompleteReadError):
                pass
            assert gov.evictions >= 1
            victim = h.server.clients.get("slowpoke")
            assert saw_disconnect or victim is None or victim.closed

            assert await await_normal(gov, timeout_s=10.0) == NORMAL
            h.server.publish_sys_topics()
            state = h.server.topics.retained.get(
                SYS_PREFIX + "/broker/overload/state"
            )
            assert state is not None and state.payload.decode() == NORMAL

            await h.server.close()
            await h.shutdown()

        asyncio.run(asyncio.wait_for(scenario(), timeout=300))
