"""Mesh federation suite (ISSUE 5): cross-worker pressure gossip, the
per-listener CONNECT admission gate, priority-weighted shedding, and the
partition-tolerant peer health machinery (SUSPECT park buffers, heal
replay, generation-stamped presence resync).

The acceptance drill: a 3-worker mesh where worker 0 is driven into SHED
by a seeded storm must raise its peers to >= THROTTLE via gossip within
one gossip interval, refuse new CONNECTs with CONNACK 0x97 while shed,
and shed zero high-priority-class publishes while low-priority quota
remains; a severed-then-healed peer link must replay parked QoS>0
forwards exactly once and converge presence filters against a
single-worker oracle.
"""

import asyncio
import time

import pytest

from mqtt_tpu.cluster import (
    _T_FRAME,
    _T_GOSSIP,
    _T_PACKET,
    PEER_PARTITIONED,
    PEER_SUSPECT,
    PEER_UP,
    Cluster,
)
from mqtt_tpu.faults import (
    FaultPlan,
    FaultyMatcher,
    StormPlan,
    asymmetric_partition,
    lose_gossip,
)
from mqtt_tpu.overload import SHED, THROTTLE, PeerPressureSignal
from mqtt_tpu.packets import PUBACK, PUBLISH, SUBACK, Subscription
from mqtt_tpu.server import Options
from mqtt_tpu.topics import TopicsIndex

from tests.test_overload import (
    FakeClock,
    StubClient,
    make_governor,
    run_publish_storm,
    storm_options,
)
from tests.test_server import (
    Harness,
    pub_packet,
    read_wire_packet,
    run,
    sub_packet,
)


# -- unit: the decayed peer-pressure signal ----------------------------------


class TestPeerPressureSignal:
    def test_state_floors_and_weight(self):
        clock = FakeClock()
        sig = PeerPressureSignal(weight=0.9, ttl_s=10.0, clock=clock)
        assert sig.value() == 0.0
        sig.observe(1, 0, 0.2)  # NORMAL peer: raw pressure only
        assert sig.value() == pytest.approx(0.9 * 0.2)
        sig.observe(2, 1, 0.1)  # THROTTLE floor beats a low raw pressure
        assert sig.value() == pytest.approx(0.9 * 0.75)
        sig.observe(3, 2, 0.3)  # SHED floor: lands the mesh in THROTTLE
        assert sig.value() == pytest.approx(0.9 * 0.95)
        # ...but NOT in SHED (no sympathetic full-mesh cascade)
        assert sig.value() < 0.90

    def test_decay_and_ageing(self):
        clock = FakeClock()
        sig = PeerPressureSignal(weight=1.0, ttl_s=10.0, clock=clock)
        sig.observe(1, 2, 1.0)
        assert sig.value() == pytest.approx(1.0)
        clock.t += 5  # half the TTL: linear decay to half
        assert sig.value() == pytest.approx(0.5)
        clock.t += 5  # TTL reached: aged out entirely AND purged
        assert sig.value() == 0.0
        assert not sig._peers

    def test_forget_drops_immediately(self):
        sig = PeerPressureSignal(weight=1.0, ttl_s=60.0)
        sig.observe(1, 2, 1.0)
        sig.forget(1)
        assert sig.value() == 0.0

    def test_governor_folds_peers_signal(self):
        gov, clock, pressure = make_governor()
        sig = gov.enable_federation(weight=0.9, ttl_s=10.0)
        assert gov.enable_federation() is sig  # idempotent
        sig.observe(7, 2, 0.4)  # one shedding peer
        assert gov.evaluate(force=True) == THROTTLE
        assert gov.signal_pressures["peers"] == pytest.approx(0.9 * 0.95)


# -- unit: CONNECT admission + priority-weighted quotas ----------------------


class TestConnectAdmission:
    def test_refuses_while_shedding_with_admin_reserve(self):
        gov, clock, pressure = make_governor(
            admission_reserve=2, eval_interval_s=1000.0, quota_window_s=10.0
        )
        assert gov.admit_connect()  # NORMAL: always
        pressure[0] = 2.0
        gov.evaluate(force=True)
        assert gov.state == SHED
        assert not gov.admit_connect(admin=False)
        assert gov.admit_connect(admin=True)  # reserve slot 1
        assert gov.admit_connect(admin=True)  # reserve slot 2
        assert not gov.admit_connect(admin=True)  # reserve exhausted
        assert gov.connects_refused == 2
        assert gov.reserve_admits == 2
        clock.t += 10  # window rolls: the reserve refills
        gov.evaluate(force=True)
        assert gov.admit_connect(admin=True)

    def test_refuses_while_throttling_too(self):
        gov, clock, pressure = make_governor(admission_reserve=0)
        pressure[0] = 0.8
        gov.evaluate(force=True)
        assert gov.state == THROTTLE
        assert not gov.admit_connect(admin=True)  # reserve 0: nobody

    def test_failed_auth_cannot_burn_the_reserve(self):
        """The admission gate runs AFTER on_connect_authenticate: a
        client claiming an admin identity with bad credentials is
        rejected 0x86 before the reserve accounting ever runs."""

        async def scenario():
            from mqtt_tpu.hooks import ON_CONNECT_AUTHENTICATE, Hook

            class Deny(Hook):
                def id(self):
                    return "deny"

                def provides(self, b):
                    return b == ON_CONNECT_AUTHENTICATE

                def on_connect_authenticate(self, cl, pk):
                    return False

            h = Harness(Options(inline_client=True), allow=False)
            h.server.add_hook(Deny())
            await h.server.serve()
            gov = h.server.overload
            gov.add_source("t", lambda: 2.0)
            gov.evaluate(force=True)
            assert gov.state == SHED
            await h.connect("admin-wannabe", version=5, expect_code=0x86)
            assert gov.reserve_admits == 0
            assert gov.connects_refused == 0  # auth failed first
            await h.server.close()
            await h.shutdown()

        run(scenario())

    def test_gauges_carry_admission_counters(self):
        gov, clock, pressure = make_governor(admission_reserve=0)
        pressure[0] = 2.0
        gov.evaluate(force=True)
        gov.admit_connect()
        g = gov.gauges()
        assert g["connects_refused"] == 1
        assert g["reserve_admits"] == 0


class TestPriorityWeightedShedding:
    def _shed_governor(self, **weights):
        gov, clock, pressure = make_governor(
            shed_quota=4,
            eval_interval_s=1000.0,
            quota_window_s=10.0,
            priority_weights=weights,
        )
        pressure[0] = 2.0
        gov.evaluate(force=True)
        assert gov.state == SHED
        return gov, clock

    def test_low_priority_sheds_first(self):
        gov, clock = self._shed_governor(low=0.25, high=4.0)
        low, high, flat = StubClient("lo"), StubClient("hi"), StubClient("fl")
        low.priority_weight = 0.25
        high.priority_weight = 4.0
        admitted = {"lo": 0, "hi": 0, "fl": 0}
        for cl, key in ((low, "lo"), (high, "hi"), (flat, "fl")):
            for _ in range(20):
                if gov.admit(cl):
                    admitted[key] += 1
        assert admitted["lo"] == 1  # int(4 * 0.25)
        assert admitted["fl"] == 4  # the flat default quota
        assert admitted["hi"] == 16  # int(4 * 4.0)

    def test_zero_weight_class_sheds_everything(self):
        gov, clock = self._shed_governor(junk=0.0)
        cl = StubClient("junk-1")
        cl.priority_weight = 0.0
        assert not gov.admit(cl)

    def test_read_delay_quota_is_weighted(self):
        gov, clock, pressure = make_governor(
            publish_quota=10, throttle_delay_s=0.02, eval_interval_s=1000.0
        )
        pressure[0] = 0.8
        gov.evaluate(force=True)
        hi = StubClient("hi")
        hi.priority_weight = 10.0
        gov.read_delay(hi)  # sync the window
        hi._pub_count = 50  # over the flat quota, under 10x
        assert gov.read_delay(hi) == 0.0
        lo = StubClient("lo")
        lo.priority_weight = 0.5
        gov.read_delay(lo)
        lo._pub_count = 8  # under the flat quota, over 0.5x
        assert gov.read_delay(lo) == pytest.approx(0.02)

    def test_server_assigns_class_at_connect(self):
        async def scenario():
            h = Harness(
                Options(
                    inline_client=True,
                    overload_priority_classes={"high": 8.0},
                    overload_priority_users={"vip": "high"},
                )
            )
            await h.server.serve()
            await h.connect("vip")
            await h.connect("pleb")
            assert h.server.clients.get("vip").priority_weight == 8.0
            assert h.server.clients.get("vip").priority_class == "high"
            assert h.server.clients.get("pleb").priority_weight == 1.0
            await h.server.close()
            await h.shutdown()

        run(scenario())


# -- unit: gossip application + destination-aware forward tiering ------------


class _FakeTransport:
    def __init__(self, buffered: int = 0) -> None:
        self.buffered = buffered
        self.aborted = False

    def get_write_buffer_size(self) -> int:
        return self.buffered

    def abort(self) -> None:
        self.aborted = True


class _FakeWriter:
    def __init__(self, buffered: int = 0) -> None:
        self.transport = _FakeTransport(buffered)
        self.sent = []

    def write(self, data: bytes) -> None:
        self.sent.append(data)


def _bare_cluster(tmp_path, with_governor=True):
    class FakeServer:
        pass

    srv = FakeServer()
    srv.topics = TopicsIndex()
    gov = None
    if with_governor:
        gov, _clock, pressure = make_governor()
        srv.overload = gov
    c = Cluster(srv, 0, 2, str(tmp_path))
    return c, gov


class TestMeshReserve:
    """Cross-worker admission-reserve coordination (ISSUE 12 satellite /
    PR 5 residual): the admin-ACL CONNECT reserve is a MESH budget —
    reserve spend gossips on _T_GOSSIP and exhausting it on one worker
    refuses reserve CONNECTs on the others."""

    def _shed(self, gov, clock):
        gov._sources["test"] = lambda: 1.0
        gov.evaluate(force=True)
        assert gov.state == SHED

    def test_reserve_exhausted_on_one_worker_refuses_on_the_other(
        self, tmp_path
    ):
        # two workers, reserve of 2 mesh-wide
        c0, gov0 = _bare_cluster(tmp_path)
        c1, gov1 = _bare_cluster(tmp_path)
        for gov in (gov0, gov1):
            gov.config.admission_reserve = 2
            gov.config.quota_window_s = 60.0
        self._shed(gov0, None)
        self._shed(gov1, None)
        # worker 0 burns the whole reserve locally
        assert gov0.admit_connect(admin=True)
        assert gov0.admit_connect(admin=True)
        assert not gov0.admit_connect(admin=True)
        assert gov0.reserve_advert() == 2
        # its advert (with the spend) reaches worker 1 over gossip
        payload = c0._advert_payload()
        assert b'"r": 2' in payload or b'"r":2' in payload
        c1._on_gossip(0, payload)
        # worker 1 now refuses reserve CONNECTs too: the budget is shared
        assert not gov1.admit_connect(admin=True)
        assert gov1.connects_refused >= 1
        assert gov1.gauges()["reserve_spent_mesh"] == 2
        assert gov1.gauges()["reserve_spent_local"] == 0

    def test_peer_reserve_spend_ages_out_after_a_window(self, tmp_path):
        c1, gov1 = _bare_cluster(tmp_path)
        gov1.config.admission_reserve = 1
        gov1.config.quota_window_s = 60.0
        self._shed(gov1, None)
        c1._on_gossip(0, b'{"s": 2, "p": 1.0, "r": 1}')
        assert not gov1.admit_connect(admin=True)
        # a window later the stale spend no longer draws from the budget
        gov1.clock.t += 61.0
        gov1.evaluate(force=True)
        assert gov1.admit_connect(admin=True)

    def test_zero_spend_advert_clears_the_peer_entry(self, tmp_path):
        c1, gov1 = _bare_cluster(tmp_path)
        gov1.config.admission_reserve = 1
        gov1.config.quota_window_s = 60.0
        self._shed(gov1, None)
        c1._on_gossip(0, b'{"s": 2, "p": 1.0, "r": 1}')
        assert gov1.gauges()["reserve_spent_mesh"] == 1
        # the peer's window rolled: its next advert carries no spend
        c1._on_gossip(0, b'{"s": 2, "p": 1.0}')
        assert gov1.gauges()["reserve_spent_mesh"] == 0
        assert 0 not in c1._peer_advert_reserve

    def test_reserve_admit_fires_immediate_gossip_observer(self):
        gov, clock, pressure = make_governor(
            admission_reserve=2, quota_window_s=60.0
        )
        pressure[0] = 1.0
        gov.evaluate(force=True)
        fired = []
        gov.on_reserve_admit = lambda: fired.append(1)
        assert gov.admit_connect(admin=True)
        assert fired == [1]
        # a refused connect fires nothing
        gov._reserve_in_epoch = 99
        assert not gov.admit_connect(admin=True)
        assert fired == [1]

    def test_tree_advert_folds_reserve_by_sum(self, tmp_path):
        c0, gov0 = _bare_cluster(tmp_path)
        gov0.config.admission_reserve = 8
        gov0.config.quota_window_s = 60.0
        self._shed(gov0, None)
        assert gov0.admit_connect(admin=True)
        # fake a tree topology with two live edges carrying spends
        import time as _time

        from mqtt_tpu.mesh_topology import Topology

        c0.topo = Topology(0, range(3), 2, boot_id=1)
        now = _time.monotonic()
        c0._peer_adverts[1] = (0, 0.0, now)
        c0._peer_adverts[2] = (0, 0.0, now)
        c0._peer_advert_reserve[1] = 2
        c0._peer_advert_reserve[2] = 3
        import json as _json

        # the advert toward a NEW edge folds local + both subtrees
        body = _json.loads(c0._advert_payload(exclude=None))
        assert body["r"] == 1 + 2 + 3
        # the advert toward edge 1 excludes edge 1's own spend
        body = _json.loads(c0._advert_payload(exclude=1))
        assert body["r"] == 1 + 3


class TestGossip:
    def test_on_gossip_feeds_adverts_and_governor(self, tmp_path):
        c, gov = _bare_cluster(tmp_path)
        c._on_gossip(1, b'{"s": 2, "p": 0.4}')
        assert c._peer_adverts[1][0] == 2
        assert gov.peer_signal is not None
        assert gov.peer_signal.value() == pytest.approx(0.9 * 0.95)
        # malformed gossip is ignored, never raises
        c._on_gossip(1, b"not json")

    def test_qos0_sheds_outright_to_shed_destination(self, tmp_path):
        c, gov = _bare_cluster(tmp_path)
        c._on_gossip(1, b'{"s": 2, "p": 1.0}')
        w = _FakeWriter(buffered=0)  # empty buffer: only the advert decides
        assert not c._send_nowait(1, w, _T_FRAME, b"f", qos=0)
        assert c.shed_qos0_forwards == 1
        assert c.dropped_backlog == 1
        assert gov.sheds == 1
        # QoS>0 still flows: the peer's governor handles it on arrival
        assert c._send_nowait(1, w, _T_PACKET, b"p", qos=1)
        # an un-advertised peer is untouched
        assert c._send_nowait(2, _FakeWriter(), _T_FRAME, b"f", qos=0)

    def test_throttle_advert_reduces_the_cap(self, tmp_path):
        c, gov = _bare_cluster(tmp_path)
        c._on_gossip(1, b'{"s": 1, "p": 0.5}')
        # 60% of the buffer: fine at the full cap, over the 0.5 tier
        w = _FakeWriter(int(0.6 * Cluster.MAX_PEER_BUFFER))
        assert not c._send_nowait(1, w, _T_FRAME, b"f", qos=0)
        assert c.shed_qos0_forwards == 1

    def test_stale_advert_ages_out(self, tmp_path):
        c, gov = _bare_cluster(tmp_path)
        c._on_gossip(1, b'{"s": 2, "p": 1.0}')
        c._peer_adverts[1] = (2, 1.0, time.monotonic() - c.advert_ttl_s - 1)
        assert c._qos0_fraction_for(1) == 1.0

    def test_lose_gossip_filter(self, tmp_path):
        c, gov = _bare_cluster(tmp_path)
        release = lose_gossip(c, rate=1.0, seed=3)
        assert not c._rx_filter(1, _T_GOSSIP, b"{}")
        assert c._rx_filter(1, _T_PACKET, b"{}")  # data untouched
        release()
        assert c._rx_filter is None


# -- unit: peer health, park buffer, partition flush -------------------------


class TestPeerHealth:
    def _interested(self, c, peer, filter="park/#"):
        c._apply_presence(peer, filter, True, False)

    def _packet(self, topic="park/t", qos=1, payload=b"x"):
        from mqtt_tpu.packets import FixedHeader, Packet

        pk = Packet(
            fixed_header=FixedHeader(type=PUBLISH, qos=qos),
            protocol_version=5,
            topic_name=topic,
            packet_id=qos,
            payload=payload,
        )
        pk.origin = "pub"
        return pk

    def test_suspect_parks_qos1_and_partition_flushes(self, tmp_path):
        c, gov = _bare_cluster(tmp_path)
        self._interested(c, 1)
        # no writer, no health record yet: the first QoS>0 forward parks
        c.forward_packet(self._packet())
        assert c.parked_forwards == 1
        assert c._health[1].park_bytes > 0
        c.forward_packet(self._packet(payload=b"y"))
        assert c.parked_forwards == 2
        assert c.dropped_qos_forwards == 0  # held, not dropped
        # the partition verdict flushes the park into the drop counters
        c._mark_partitioned(1)
        assert c._health[1].state == PEER_PARTITIONED
        assert c.parked_forwards == 0
        assert c.dropped_partition == 2
        assert c.dropped_qos_forwards == 2
        assert c.dropped_forwards == 2
        # PARTITIONED also withdrew the peer's stale interest: further
        # publishes simply stop matching it (no forward, no drop)
        assert c._interested_peers("park/t") == ()
        c.forward_packet(self._packet(payload=b"z"))
        assert c.dropped_partition == 2

    def test_qos0_never_parks(self, tmp_path):
        c, gov = _bare_cluster(tmp_path)
        self._interested(c, 1)
        c.forward_frame("park/t", b"\x30\x02..", "pub")
        assert c.parked_forwards == 0
        assert c.dropped_partition == 1  # link-down drop, counted

    def test_park_buffer_is_bounded(self, tmp_path):
        c, gov = _bare_cluster(tmp_path)
        self._interested(c, 1)
        c.park_max_bytes = 400
        for i in range(10):
            c.forward_packet(self._packet(payload=bytes(100)))
        ph = c._health[1]
        assert ph.park_bytes <= c.park_max_bytes + 200  # one frame slack
        assert c.dropped_partition > 0  # the spill is counted
        assert c.parked_forwards == len(ph.park)

    def test_heal_replays_exactly_once(self, tmp_path):
        c, gov = _bare_cluster(tmp_path)
        self._interested(c, 1)
        c.forward_packet(self._packet())
        c.forward_packet(self._packet(payload=b"y"))
        assert c.parked_forwards == 2
        w = _FakeWriter()
        c._heal_peer(1, w)
        assert c._health[1].state == PEER_UP
        assert c.replayed_forwards == 2
        assert c.parked_forwards == 0
        assert len(w.sent) == 2
        # a second heal replays nothing (the park is empty)
        c._heal_peer(1, w)
        assert c.replayed_forwards == 2

    def test_ping_loop_thresholds(self, tmp_path):
        """Synthetic missed-pong aging: suspect at the suspect threshold,
        partitioned (with a link abort) at the partition threshold."""

        async def scenario():
            c, gov = _bare_cluster(tmp_path)
            c.PING_INTERVAL_S = 0.01
            c.suspect_pings = 2
            c.partition_pings = 4
            w = _FakeWriter()
            c._writers[1] = w
            c._loop = asyncio.get_running_loop()
            task = asyncio.get_running_loop().create_task(c._ping_loop())
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                ph = c._health.get(1)
                if ph is not None and ph.state == PEER_PARTITIONED:
                    break
                await asyncio.sleep(0.01)
            ph = c._health[1]
            assert ph.state == PEER_PARTITIONED
            assert w.transport.aborted  # the link is forced down for re-dial
            task.cancel()

        run(scenario())

    def test_pong_resets_and_heals(self, tmp_path):
        c, gov = _bare_cluster(tmp_path)
        ph = c._health_for(1)
        ph.state = PEER_SUSPECT
        ph.outstanding = 3
        c._writers[1] = _FakeWriter()
        self._interested(c, 1)
        c._park(1, _T_PACKET, b"held")
        c._on_pong(1, b"\x00" * 8)
        assert ph.outstanding == 0
        assert ph.state == PEER_UP
        assert c.replayed_forwards == 1  # the park replayed on heal

    def test_sync_clears_stale_presence(self, tmp_path):
        c, gov = _bare_cluster(tmp_path)
        c._apply_presence(1, "old/t", True, False)
        assert c._interested_peers("old/t") == (1,)
        c._apply_sync(1, gen=5)
        assert c._interested_peers("old/t") == ()
        # an older generation's sync arriving late is ignored
        c._apply_presence(1, "new/t", True, False)
        c._apply_sync(1, gen=3)
        assert c._interested_peers("new/t") == (1,)

    def test_restarted_peer_generation_wins(self, tmp_path):
        """A RESTARTED peer's generation counter begins again at 1; its
        fresh sync must win against the dead incarnation's high-water
        mark (the boot nonce distinguishes incarnations), and the dead
        incarnation's leftover presence must stay discarded."""
        c, gov = _bare_cluster(tmp_path)
        c._apply_sync(1, gen=5, boot=111)
        c._apply_presence(1, "old/t", True, False)
        assert not c._presence_stale(1, {"gen": 5, "boot": 111})
        # the peer process restarts: new boot id, counter back at 1
        c._apply_sync(1, gen=1, boot=222)
        assert c._interested_peers("old/t") == ()  # cleared by the sync
        assert not c._presence_stale(1, {"gen": 1, "boot": 222})
        # the dead incarnation's frames never re-apply, whatever the gen
        assert c._presence_stale(1, {"gen": 99, "boot": 111})
        # a peer too old to send boot ids only checks the generation
        assert c._presence_stale(1, {"gen": 0})
        assert not c._presence_stale(1, {"gen": 1})


# -- e2e: severed-then-healed link replays parked QoS>0 exactly once ---------


class TestSeverHealReplay:
    def test_park_replay_and_presence_convergence(self, tmp_path):
        async def scenario():
            h0 = Harness(Options(inline_client=True))
            h1 = Harness(Options(inline_client=True))
            c0 = Cluster(h0.server, 0, 2, str(tmp_path))
            c1 = Cluster(h1.server, 1, 2, str(tmp_path))
            for c in (c0, c1):
                c.PING_INTERVAL_S = 0.2
            c1.DIAL_BACKOFF_S = 0.3  # a parking window before the re-dial
            await h0.server.serve()
            await h1.server.serve()
            await c0.start()
            await c1.start()

            async def wait_for(cond, timeout=10.0):
                deadline = time.monotonic() + timeout
                while time.monotonic() < deadline:
                    if cond():
                        return True
                    await asyncio.sleep(0.02)
                return False

            assert await wait_for(lambda: c0.peer_count == 1 and c1.peer_count == 1)

            sr, sw, _ = await h1.connect("sub", version=5)
            sw.write(sub_packet(1, [Subscription(filter="park/t", qos=1)], version=5))
            await sw.drain()
            assert (await read_wire_packet(sr, 5)).fixed_header.type == SUBACK
            assert await wait_for(lambda: c0._interested_peers("park/t") == (1,))

            pr, pw, _ = await h0.connect("pub", version=5)

            got: list[bytes] = []

            async def collect():
                while True:
                    try:
                        pk = await asyncio.wait_for(read_wire_packet(sr, 5), 0.5)
                    except asyncio.TimeoutError:
                        if done.is_set():
                            return
                        continue
                    if pk.fixed_header.type == PUBLISH:
                        got.append(bytes(pk.payload))

            done = asyncio.Event()
            collector = asyncio.ensure_future(collect())

            # sanity: the live link forwards
            pw.write(pub_packet("park/t", b"pre", qos=1, pid=1, version=5))
            await pw.drain()
            assert (await read_wire_packet(pr, 5)).fixed_header.type == PUBACK
            assert await wait_for(lambda: b"pre" in got)

            # sever mid-traffic and park five QoS1 publishes
            c0._writers[1].transport.abort()
            assert await wait_for(lambda: c0._writers.get(1) is None)
            assert c0._health[1].state == PEER_SUSPECT
            for i in range(5):
                pw.write(
                    pub_packet("park/t", f"held-{i}".encode(), qos=1,
                               pid=2 + i, version=5)
                )
            await pw.drain()
            for _ in range(5):
                assert (await read_wire_packet(pr, 5)).fixed_header.type == PUBACK
            assert c0.parked_forwards == 5
            assert c0.dropped_qos_forwards == 0

            # heal: the dialer reconnects, the park replays exactly once
            assert await wait_for(lambda: c0.peer_count == 1)
            assert await wait_for(lambda: c0.replayed_forwards == 5)
            assert await wait_for(
                lambda: sum(1 for p in got if p.startswith(b"held-")) >= 5
            )
            await asyncio.sleep(0.3)  # a duplicate would land here
            done.set()
            await collector
            for i in range(5):
                assert got.count(b"held-%d" % i) == 1, (i, got)
            assert c0.parked_forwards == 0

            # presence converges against the single-worker oracle: the
            # healed mesh's interest map must mirror worker 1's live trie
            # high packet id: ids 1..6 are inflight (the unacked QoS1
            # deliveries above), and a SUBSCRIBE on an inflight id is
            # refused with 0x91 packet-identifier-in-use
            sw.write(sub_packet(600, [Subscription(filter="late/+", qos=0)], version=5))
            await sw.drain()
            assert await wait_for(lambda: c0._interested_peers("late/x") == (1,))
            oracle = h1.server.topics
            for topic in ("park/t", "late/x", "nobody/here"):
                expect = (1,) if oracle.subscribers(topic).subscriptions else ()
                assert await wait_for(
                    lambda t=topic, e=expect: c0._interested_peers(t) == e
                ), topic

            await c0.stop()
            await c1.stop()
            await h0.server.close()
            await h1.server.close()
            await h0.shutdown()
            await h1.shutdown()

        run(scenario())

    def test_asymmetric_partition_parks_then_heals(self, tmp_path):
        """One-way loss (pongs vanish, writes still succeed): the health
        clock walks the peer to SUSPECT and QoS>0 forwards park; when the
        return path heals, the next pong replays them."""

        async def scenario():
            h0 = Harness(Options(inline_client=True))
            h1 = Harness(Options(inline_client=True))
            c0 = Cluster(h0.server, 0, 2, str(tmp_path))
            c1 = Cluster(h1.server, 1, 2, str(tmp_path))
            for c in (c0, c1):
                c.PING_INTERVAL_S = 0.05
            c0.suspect_pings = 2
            c0.partition_pings = 60  # keep the drill inside SUSPECT
            await h0.server.serve()
            await h1.server.serve()
            await c0.start()
            await c1.start()

            async def wait_for(cond, timeout=10.0):
                deadline = time.monotonic() + timeout
                while time.monotonic() < deadline:
                    if cond():
                        return True
                    await asyncio.sleep(0.02)
                return False

            assert await wait_for(lambda: c0.peer_count == 1 and c1.peer_count == 1)
            c0._apply_presence(1, "asym/t", True, False)

            release = asymmetric_partition(c0, 1)
            assert await wait_for(
                lambda: c0._health.get(1) is not None
                and c0._health[1].state == PEER_SUSPECT
            )
            from tests.test_federation import TestPeerHealth

            c0.forward_packet(TestPeerHealth()._packet(topic="asym/t"))
            assert c0.parked_forwards == 1

            release()  # the return path heals; the next pong replays
            assert await wait_for(lambda: c0.replayed_forwards == 1)
            assert c0._health[1].state == PEER_UP
            assert c0.parked_forwards == 0

            await c0.stop()
            await c1.stop()
            await h0.server.close()
            await h1.server.close()
            await h0.shutdown()
            await h1.shutdown()

        run(scenario())


# -- e2e: the 3-worker gossip acceptance drill -------------------------------


class TestMeshFederationStorm:
    def test_shed_worker_raises_mesh_refuses_connects_and_weights_sheds(
        self, tmp_path
    ):
        """Worker 0 is stormed into SHED (seeded): its peers reach >=
        THROTTLE via gossip within one (shortened) gossip interval, a new
        CONNECT to worker 0 gets CONNACK 0x97, and the high-priority
        client sheds NOTHING while low-priority publishers do."""

        async def scenario():
            low_users = {f"storm-p{i}": "low" for i in range(5)}
            h0 = Harness(
                storm_options(
                    dwell_ms=4000.0,  # sticky SHED for the probes below
                    shed_exit=0.02,
                    shed_quota=10,
                    overload_admission_reserve=0,
                    overload_priority_classes={"low": 0.1, "high": 50.0},
                    overload_priority_users={**low_users, "vip": "high"},
                )
            )
            h0.server.matcher = FaultyMatcher(
                h0.server.matcher, FaultPlan(seed=5, slow_rate=1.0, slow_s=0.02)
            )
            h1 = Harness(Options(inline_client=True))
            h2 = Harness(Options(inline_client=True))
            clusters = [
                Cluster(h.server, i, 3, str(tmp_path))
                for i, h in enumerate((h0, h1, h2))
            ]
            for c in clusters:
                c.PING_INTERVAL_S = 0.1  # the shortened gossip interval
            for h in (h0, h1, h2):
                await h.server.serve()
            for c in clusters:
                await c.start()

            async def wait_for(cond, timeout=10.0):
                deadline = time.monotonic() + timeout
                while time.monotonic() < deadline:
                    if cond():
                        return True
                    await asyncio.sleep(0.02)
                return False

            assert await wait_for(
                lambda: all(c.peer_count == 2 for c in clusters)
            )

            gov0 = h0.server.overload
            # the vip connects BEFORE the storm (a high-priority session
            # surviving the blast, not racing the admission gate)
            vip_r, vip_w, _ = await h0.connect("vip", version=5)

            plan = StormPlan(
                seed=42, publishers=5, msgs_per_publisher=60,
                topic_space=8, qos1_fraction=0.5,
            )
            admitted, shed, _ack_times, collector, _ = await run_publish_storm(
                h0, plan
            )
            await collector.finish()
            assert shed, "the storm never shed: offered load too low"
            assert gov0.state == SHED  # dwell keeps the posture sticky

            # (1) gossip raises the peers within one gossip interval:
            # poll well inside ONE production interval; the transition
            # gossip plus the 0.1s cadence deliver the advert, and the
            # peers' own evaluation folds it into their posture
            t0 = time.monotonic()
            for gov in (h1.server.overload, h2.server.overload):
                assert await wait_for(
                    lambda g=gov: g.evaluate(force=True) in (THROTTLE, SHED),
                    timeout=2.0,
                ), "peer governor never left NORMAL"
                assert gov.signal_pressures.get("peers", 0.0) >= 0.7
                assert gov.state == THROTTLE  # raised, NOT a SHED cascade
            assert time.monotonic() - t0 < 2.0

            # (2) a new CONNECT is refused with CONNACK 0x97 while shed
            await h0.connect("late-joiner", version=5, expect_code=0x97)
            assert gov0.connects_refused >= 1

            # (3) priority-weighted shedding: the vip's weighted quota
            # (10 x 50) admits everything it sends while low-priority
            # budgets (10 x 0.1 = 1/window) are already shedding
            assert gov0.state == SHED
            vip_acks = []
            for i in range(20):
                vip_w.write(
                    pub_packet("storm/vip/t", b"vip", qos=1, pid=1 + i, version=5)
                )
            await vip_w.drain()
            while len(vip_acks) < 20:
                pk = await asyncio.wait_for(read_wire_packet(vip_r, 5), 10)
                if pk.fixed_header.type == PUBACK:
                    vip_acks.append(pk.reason_code)
            assert all(code != 0x97 for code in vip_acks), vip_acks
            # ...and the shed set really was low-priority traffic
            assert shed and all(tag[:1] == b"s" for tag in shed)

            for c in clusters:
                await c.stop()
            for h in (h0, h1, h2):
                await h.server.close()
                await h.shutdown()

        run(scenario())


# -- config plumbing ---------------------------------------------------------


class TestFederationConfig:
    def test_knob_normalization(self):
        o = Options(
            overload_federation_weight=-1.0,
            overload_federation_ttl_ms=0,
            overload_admission_reserve=-3,
            cluster_peer_health_suspect_pings=0,
            cluster_peer_health_partition_pings=0,
            cluster_peer_park_max_bytes=-1,
        )
        o.ensure_defaults()
        assert o.overload_federation_weight > 0
        assert o.overload_federation_ttl_ms > 0
        assert o.overload_admission_reserve == 0
        assert o.cluster_peer_health_suspect_pings > 0
        assert (
            o.cluster_peer_health_partition_pings
            > o.cluster_peer_health_suspect_pings
        )
        assert o.cluster_peer_park_max_bytes > 0

    def test_config_file_passthrough(self):
        from mqtt_tpu.config import from_bytes

        opts = from_bytes(
            b"""
options:
  overload_federation: false
  overload_federation_weight: 0.8
  overload_admission_reserve: 5
  overload_priority_classes: {low: 0.2, high: 4.0}
  overload_priority_users: {sensor-fleet: low}
  cluster_peer_health_suspect_pings: 3
  cluster_peer_park_max_bytes: 65536
listeners:
  - type: tcp
    id: ops
    address: 127.0.0.1:0
    admission: false
"""
        )
        assert opts.overload_federation is False
        assert opts.overload_federation_weight == 0.8
        assert opts.overload_admission_reserve == 5
        assert opts.overload_priority_classes == {"low": 0.2, "high": 4.0}
        assert opts.overload_priority_users == {"sensor-fleet": "low"}
        assert opts.cluster_peer_health_suspect_pings == 3
        assert opts.cluster_peer_park_max_bytes == 65536
        assert opts.listeners[0].admission is False

    def test_drain_refuses_with_0x89(self):
        async def scenario():
            h = Harness(Options(inline_client=True))
            await h.server.serve()
            h.server._draining = True
            await h.connect("late", version=5, expect_code=0x89)
            h.server._draining = False
            await h.server.close()
            await h.shutdown()

        run(scenario())

    def test_admission_exempt_listener(self):
        async def scenario():
            from mqtt_tpu.listeners import MockListener

            h = Harness(
                Options(inline_client=True, overload_admission_reserve=0)
            )
            lst = MockListener("ops", "1")
            lst.config.admission = False
            h.server.add_listener(lst)
            await h.server.serve()
            pressure = [2.0]
            h.server.overload.add_source("test", lambda: pressure[0])
            h.server.overload.evaluate(force=True)
            assert h.server.overload.state == SHED
            # the exempt listener admits; the default path refuses
            assert h.server._connect_admission(
                h.server.new_client(None, None, "ops", "x", False), "ops"
            ) is None
            refusal = h.server._connect_admission(
                h.server.new_client(None, None, "t1", "y", False), "t1"
            )
            assert refusal is not None and refusal.code == 0x97
            pressure[0] = 0.0
            await h.server.close()
            await h.shutdown()

        run(scenario())

