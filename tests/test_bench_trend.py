"""Unit tests for the bench-history trend gate (exp/bench_trend.py),
grown in ISSUE 15 with the per-config scalar gate that watches cfg 8's
``receive_flatness_ratio`` beside the headline."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from exp.bench_trend import (  # noqa: E402
    CONFIG_SCALARS,
    check_config_scalar,
    check_trend,
    load_history,
    usable_rounds,
)


def entry(value, flatness=None, round_tag="r", metric="m"):
    e = {"round": round_tag, "metric": metric, "value": value, "configs": {}}
    if flatness is not None:
        e["configs"]["8_publish_storm"] = {"receive_flatness_ratio": flatness}
    return e


class TestHeadlineTrend:
    def test_regression_fails(self):
        entries = [entry(100), entry(110), entry(100), entry(60)]
        ok, msg = check_trend(entries)
        assert not ok and "REGRESSION" in msg

    def test_within_threshold_passes(self):
        entries = [entry(100), entry(110), entry(100), entry(90)]
        ok, _ = check_trend(entries)
        assert ok

    def test_too_few_rounds_pass(self):
        ok, msg = check_trend([entry(100)])
        assert ok and "nothing to gate" in msg


class TestConfigScalarGate:
    def test_flatness_regression_fails(self):
        entries = [
            entry(100, flatness=0.5),
            entry(100, flatness=0.6),
            entry(100, flatness=0.55),
            entry(100, flatness=0.2),  # > 25% below the 0.55 median
        ]
        ok, msg = check_config_scalar(entries, "8_publish_storm", "receive_flatness_ratio")
        assert not ok and "REGRESSION" in msg

    def test_flatness_within_threshold_passes(self):
        entries = [
            entry(100, flatness=0.5),
            entry(100, flatness=0.6),
            entry(100, flatness=0.5),
        ]
        ok, _ = check_config_scalar(entries, "8_publish_storm", "receive_flatness_ratio")
        assert ok

    def test_rounds_without_the_scalar_are_skipped(self):
        entries = [
            entry(100),  # pre-ISSUE-15 round: no flatness scalar
            entry(100, flatness=0.5),
            entry(100, flatness=0.52),
        ]
        ok, msg = check_config_scalar(entries, "8_publish_storm", "receive_flatness_ratio")
        assert ok

    def test_newest_round_without_scalar_passes_with_notice(self):
        entries = [
            entry(100, flatness=0.5),
            entry(100, flatness=0.6),
            entry(100),  # newest skipped cfg 8: must not be judged
        ]
        ok, msg = check_config_scalar(entries, "8_publish_storm", "receive_flatness_ratio")
        assert ok and "did not measure" in msg

    def test_too_few_usable_rounds_pass(self):
        ok, msg = check_config_scalar(
            [entry(100, flatness=0.5)], "8_publish_storm",
            "receive_flatness_ratio",
        )
        assert ok and "nothing to gate" in msg

    def test_flatness_is_a_registered_scalar(self):
        assert ("8_publish_storm", "receive_flatness_ratio") in CONFIG_SCALARS


class TestLedgerHoist:
    def test_history_config_block_keeps_top_level_scalars(self):
        from bench import _history_config_block

        block = _history_config_block(
            {
                "receive_flatness_ratio": 0.42,
                "receive_flatness": {"nested": "dropped"},
                "cells": [1, 2, 3],
            }
        )
        assert block == {"receive_flatness_ratio": 0.42}
