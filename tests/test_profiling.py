"""The host hot-path observatory (mqtt_tpu.profiling +
mqtt_tpu.utils.locked): sampler determinism under a seeded synthetic
thread workload, the collapsed-stack and trace-event exports + their
validators, lock-plane wait/hold math, fan-out amplification accounting
against a known fan-out, space-saving sketch accuracy bounds, and the
GET /profile HTTP matrix.
"""

import asyncio
import json
import threading
import time

import pytest

from mqtt_tpu import Options, Server
from mqtt_tpu.listeners import Config as LConfig, HTTPStats
from mqtt_tpu.packets import SUBACK, Subscription
from mqtt_tpu.profiling import (
    SamplingProfiler,
    TopicSketch,
    check_collapsed,
)
from mqtt_tpu.tracing import check_trace_events
from mqtt_tpu.utils.locked import (
    DEFAULT_PLANE,
    InstrumentedLock,
    LockPlane,
    LockedMap,
)

from tests.test_server import (
    Harness,
    pub_packet,
    read_wire_packet,
    run,
    sub_packet,
)

TIMEOUT = 5


# -- deterministic sampler: synthetic frames ---------------------------------


class _FakeCode:
    def __init__(self, name, filename):
        self.co_name = name
        self.co_filename = filename


class _FakeFrame:
    """A minimal stand-in for an interpreter frame: f_code/f_lineno/f_back."""

    def __init__(self, name, lineno, back=None, filename="synthetic.py"):
        self.f_code = _FakeCode(name, filename)
        self.f_lineno = lineno
        self.f_back = back


def _stack(*names):
    """Build a frame chain; names given root-first, returns the LEAF."""
    frame = None
    for i, name in enumerate(names):
        frame = _FakeFrame(name, 10 + i, back=frame)
    return frame


class TestSamplerDeterminism:
    def _profiler(self, frames_by_sweep):
        """A profiler fed a scripted sequence of _current_frames dicts
        and a scripted clock — fully deterministic."""
        sweeps = iter(frames_by_sweep)
        t = [0.0]

        def clock():
            t[0] += 0.005
            return t[0]

        return SamplingProfiler(
            hz=100.0, frames_fn=lambda: next(sweeps), clock=clock
        )

    def test_collapsed_aggregation_and_counts(self):
        leaf = _stack("serve", "fan_out", "encode")
        p = self._profiler([{1: leaf}, {1: leaf}, {1: leaf}])
        for _ in range(3):
            p.sample_once()
        txt = p.collapsed()
        assert check_collapsed(txt) == 1  # one distinct stack
        line = txt.strip()
        assert line.endswith(" 3")
        # root-first order: serve;fan_out;encode
        assert line.index("serve") < line.index("fan_out") < line.index("encode")
        assert "(synthetic.py:" in line
        assert p.samples == 3 and p.thread_samples == 3

    def test_distinct_stacks_and_thread_names(self):
        a = _stack("loop", "read")
        b = _stack("loop", "write")
        p = self._profiler([{1: a, 2: b}, {1: a, 2: b}])
        p.sample_once()
        p.sample_once()
        txt = p.collapsed()
        assert check_collapsed(txt) == 2
        # unnamed tids fall back to a stable synthetic thread name
        assert "thread-1;" in txt and "thread-2;" in txt

    def test_own_thread_never_sampled(self):
        own = threading.get_ident()
        leaf = _stack("me")
        p = self._profiler([{own: leaf, 99: leaf}])
        assert p.sample_once() == 1  # only the foreign thread
        assert "me" in p.collapsed()

    def test_stack_cap_counts_drops(self):
        p = SamplingProfiler(
            hz=10, frames_fn=lambda: {}, clock=time.perf_counter, max_stacks=16
        )
        for i in range(40):
            p._agg[("t", (f"f{i}",))] = 1  # simulate 16-cap overflow input
        # cap enforcement happens on the sample path:
        sweeps = iter([{7: _stack(f"g{i}")} for i in range(40)])
        p2 = SamplingProfiler(hz=10, frames_fn=lambda: next(sweeps), max_stacks=16)
        for _ in range(40):
            p2.sample_once()
        assert len(p2._agg) == 16
        assert p2.dropped_stacks == 24

    def test_trace_events_merge_consecutive_samples(self):
        """Three identical samples then a divergence at depth 1 become
        one long span per shared frame plus split spans below it."""
        a = _stack("root", "walk")
        b = _stack("root", "encode")
        p = self._profiler([{5: a}, {5: a}, {5: b}])
        for _ in range(3):
            p.sample_once()
        doc = p.trace_events()
        assert check_trace_events(doc) > 0
        names = [e["name"] for e in doc["traceEvents"]]
        roots = [e for e in doc["traceEvents"] if "root" in e["name"]]
        assert len(roots) == 1  # merged across all three samples
        assert any("walk" in n for n in names)
        assert any("encode" in n for n in names)
        walk = next(e for e in doc["traceEvents"] if "walk" in e["name"])
        root = roots[0]
        assert root["dur"] >= walk["dur"]

    def test_live_thread_sampling_lands_known_function(self):
        """A real (non-scripted) sweep over a live thread parked in a
        distinctively-named function finds that function."""
        ev = threading.Event()

        def profiling_target_fn():
            ev.wait(TIMEOUT)

        t = threading.Thread(target=profiling_target_fn, daemon=True, name="px")
        t.start()
        try:
            p = SamplingProfiler(hz=100)
            time.sleep(0.02)  # let the worker reach the wait
            p.sample_once()
            txt = p.collapsed()
            assert "profiling_target_fn" in txt
            assert "px;" in txt
        finally:
            ev.set()
            t.join(TIMEOUT)

    def test_start_stop_thread_lifecycle(self):
        p = SamplingProfiler(hz=200)
        p.start()
        try:
            deadline = time.monotonic() + TIMEOUT
            while p.samples == 0 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert p.samples > 0
        finally:
            p.stop()
        assert p._thread is None


# -- validators --------------------------------------------------------------


class TestCheckCollapsed:
    def test_accepts_valid(self):
        good = "main;f (x.py:1);g (x.py:2) 5\nother;h (y.py:3) 1\n"
        assert check_collapsed(good) == 2

    def test_rejects_bad_count(self):
        with pytest.raises(ValueError):
            check_collapsed("main;f 0\n")
        with pytest.raises(ValueError):
            check_collapsed("main;f notanumber\n")

    def test_rejects_empty_frame_and_empty_doc(self):
        with pytest.raises(ValueError):
            check_collapsed("main;;f 3\n")
        with pytest.raises(ValueError):
            check_collapsed("\n\n")

    def test_profile_trace_export_passes_trace_checker(self):
        p = SamplingProfiler(hz=100, frames_fn=lambda: {3: _stack("a", "b")})
        p.sample_once()
        n = check_trace_events(json.dumps(p.trace_events()))
        assert n >= 2  # one span per open frame depth


# -- lock plane --------------------------------------------------------------


class TestLockPlane:
    def test_disarmed_lock_records_nothing(self):
        plane = LockPlane()
        lk = InstrumentedLock("topics_trie", plane=plane)
        with lk:
            pass
        st = plane.stats("topics_trie")
        assert st.acquisitions == 0 and st.hold_hist.count == 0

    def test_armed_uncontended_hold_math(self):
        plane = LockPlane()
        plane.arm()
        lk = InstrumentedLock("clients", plane=plane)
        for _ in range(5):
            with lk:
                pass
        st = plane.stats("clients")
        assert st.acquisitions == 5
        assert st.contended == 0
        assert st.hold_hist.count == 5
        assert st.wait_hist.count == 0  # wait histogram only on contention
        assert st.hold_s > 0.0

    def test_contended_wait_is_measured(self):
        plane = LockPlane()
        plane.arm()
        lk = InstrumentedLock("flight_ring", plane=plane)
        entered = threading.Event()
        release = threading.Event()

        def holder():
            with lk:
                entered.set()
                release.wait(TIMEOUT)

        t = threading.Thread(target=holder, daemon=True)
        t.start()
        assert entered.wait(TIMEOUT)
        waited = [0.0]

        def contender():
            t0 = time.perf_counter()
            with lk:
                waited[0] = time.perf_counter() - t0

        c = threading.Thread(target=contender, daemon=True)
        c.start()
        time.sleep(0.05)  # let the contender actually block
        release.set()
        t.join(TIMEOUT)
        c.join(TIMEOUT)
        st = plane.stats("flight_ring")
        assert st.acquisitions == 2
        assert st.contended == 1
        assert st.wait_hist.count == 1
        # the measured wait must be in the ballpark of the real block
        assert st.wait_s == pytest.approx(waited[0], rel=0.5, abs=0.05)
        assert st.wait_s >= 0.04

    def test_rlock_reentry_times_outermost_only(self):
        plane = LockPlane()
        plane.arm()
        lk = InstrumentedLock("topics_trie", rlock=True, plane=plane)
        with lk:
            with lk:
                with lk:
                    pass
        st = plane.stats("topics_trie")
        assert st.acquisitions == 1
        assert st.hold_hist.count == 1

    def test_top_contended_and_wait_share(self):
        plane = LockPlane()
        hot = plane.stats("clients")
        cold = plane.stats("retained")
        hot.wait_s = 3.0
        hot.acquisitions = 10
        cold.wait_s = 1.0
        cold.acquisitions = 10
        top = plane.top_contended(2)
        assert [t["name"] for t in top] == ["clients", "retained"]
        assert plane.wait_share("clients") == pytest.approx(0.75)
        assert plane.wait_share("retained") == pytest.approx(0.25)

    def test_same_name_shares_stats_and_reset(self):
        plane = LockPlane()
        plane.arm()
        a = InstrumentedLock("trace_ring", plane=plane)
        b = InstrumentedLock("trace_ring", plane=plane)
        with a:
            pass
        with b:
            pass
        assert plane.stats("trace_ring").acquisitions == 2
        plane.reset()
        assert plane.stats("trace_ring").acquisitions == 0

    def test_arm_refcounting(self):
        plane = LockPlane()
        plane.arm()
        plane.arm()
        plane.disarm()
        assert plane.enabled  # second holder still live
        plane.disarm()
        assert not plane.enabled

    def test_disarm_mid_hold_keeps_depth_coherent(self):
        """Disarming while a thread HOLDS the lock must still unwind the
        re-entrancy depth on release, or stats go silently blind after a
        later re-arm (bench storm -> flatness rounds)."""
        plane = LockPlane()
        plane.arm()
        lk = InstrumentedLock("overload_governor", plane=plane)
        lk.acquire()  # depth 0 -> 1 while armed
        plane.disarm()
        lk.release()  # disarmed: must STILL decrement depth
        plane.arm()
        with lk:
            pass
        st = plane.stats("overload_governor")
        assert st.acquisitions == 2  # the re-armed acquire was outermost
        assert st.hold_hist.count == 1  # mid-hold disarm skipped its observe

    def test_reset_zeroes_in_place_for_live_locks(self):
        """reset() must zero the records live locks already hold, not
        replace them — otherwise pre-reset locks keep feeding orphans
        while top_contended reads fresh zeroed copies."""
        plane = LockPlane()
        plane.arm()
        lk = InstrumentedLock("clients", plane=plane)
        with lk:
            pass
        st_before = plane.stats("clients")
        plane.reset()
        assert st_before.acquisitions == 0
        with lk:
            pass
        assert plane.stats("clients") is st_before
        assert plane.stats("clients").acquisitions == 1
        assert plane.top_contended(3)[0]["acquisitions"] == 1

    def test_named_locked_map_instruments(self):
        plane_was = DEFAULT_PLANE.enabled
        DEFAULT_PLANE.arm()
        try:
            base = DEFAULT_PLANE.stats("retained").acquisitions
            m = LockedMap(name="retained")
            m.add("k", 1)
            assert m.get("k") == 1
            assert DEFAULT_PLANE.stats("retained").acquisitions >= base + 2
        finally:
            DEFAULT_PLANE.disarm()
            assert DEFAULT_PLANE.enabled == plane_was or DEFAULT_PLANE.enabled

    def test_non_blocking_acquire_contract(self):
        plane = LockPlane()
        plane.arm()
        lk = InstrumentedLock("matcher_breaker", plane=plane)
        got = lk.acquire(blocking=False)
        assert got
        results = []

        def try_it():
            results.append(lk.acquire(blocking=False))

        t = threading.Thread(target=try_it, daemon=True)
        t.start()
        t.join(TIMEOUT)
        assert results == [False]
        lk.release()


# -- topic sketch ------------------------------------------------------------


class TestTopicSketch:
    def test_exact_when_under_capacity(self):
        sk = TopicSketch(k=16)
        for i in range(10):
            for _ in range(i + 1):
                sk.observe(f"t/{i}")
        top = sk.top(3)
        assert top[0] == {"topic": "t/9", "count": 10, "err": 0}
        assert sk.tracked == 10
        assert sk.evictions == 0
        assert sk.total == sum(range(1, 11))

    def test_space_saving_error_bounds(self):
        """Every tracked count is within `err` of the true count, and a
        topic whose true count exceeds min_count is guaranteed tracked
        (the Metwally guarantees the compaction sizing relies on)."""
        import random

        rng = random.Random(7)
        sk = TopicSketch(k=32)
        true: dict = {}
        # zipf-ish: a few hot topics, a long cold tail
        for _ in range(5000):
            if rng.random() < 0.6:
                t = f"hot/{rng.randrange(8)}"
            else:
                t = f"cold/{rng.randrange(800)}"
            true[t] = true.get(t, 0) + 1
            sk.observe(t)
        tracked = {d["topic"]: d for d in sk.top(32)}
        for topic, d in tracked.items():
            assert true[topic] <= d["count"], "sketch must never undercount"
            assert d["count"] - d["err"] <= true[topic]
        floor = sk.min_count()
        for topic, n in true.items():
            if n > floor:
                assert topic in tracked, (topic, n, floor)

    def test_avg_hits_is_a_lower_bound(self):
        sk = TopicSketch(k=8)
        for _ in range(40):
            sk.observe("hot")
        for i in range(10):
            sk.observe(f"cold/{i}")
        true_avg = 50 / 11
        assert 0 < sk.avg_hits_per_topic() <= true_avg + 1e-9

    def test_bench_block_shape(self):
        sk = TopicSketch(k=8)
        sk.observe("a")
        b = sk.bench_block()
        assert b["observed"] == 1 and b["tracked"] == 1
        assert b["top_topics"][0]["topic"] == "a"


# -- amplification accounting vs a known fan-out -----------------------------


class TestFanoutAmplification:
    def test_qos1_fanout_encodes_once_per_variant(self):
        """QoS1 publish to N same-variant QoS1 subscribers: the batched
        fan-out (ISSUE 13) encodes the wire frame ONCE and patches each
        target's packet id at flush — encodes == variants == 1,
        deliveries == N, amplification ~1 (the exact waste ROADMAP
        item 3 named, eliminated). Every subscriber still receives a
        distinct, valid packet id."""

        async def scenario():
            h = Harness(Options(inline_client=True, telemetry_sample=1))
            subs = []
            n = 4
            for i in range(n):
                r, w, _ = await h.connect(f"s{i}", version=5)
                w.write(
                    sub_packet(
                        1, [Subscription(filter="amp/t", qos=1)], version=5
                    )
                )
                await w.drain()
                assert (await read_wire_packet(r, 5)).fixed_header.type == SUBACK
                subs.append((r, w))
            pr, pw, _ = await h.connect("pub", version=5)
            pw.write(pub_packet("amp/t", b"x", qos=1, pid=9, version=5))
            await pw.drain()
            for r, _w in subs:
                pk = await read_wire_packet(r, 5)
                assert pk.topic_name == "amp/t"
                assert pk.fixed_header.qos == 1
                # a real per-target id was patched over the shared
                # encode (ids are per-client spaces [MQTT-2.2.1])
                assert pk.packet_id > 0
            tele = h.server.telemetry
            block = tele.fanout_block(h.server.info.messages_received)
            assert block["inbound_publishes"] == 1
            assert block["publish_encodes"] == 1
            assert block["fanout_variants"] == 1
            assert block["fanout_deliveries"] == n
            assert block["encode_amplification"] == pytest.approx(1)
            assert block["encode_per_variant"] == pytest.approx(1)
            assert block["outbound_bytes"] > 0
            await h.shutdown()

        run(scenario())

    def test_qos1_fanout_legacy_knob_encodes_per_target(self):
        """``fanout_batch=False`` restores the per-subscriber encode
        path — the A/B the bench's BENCH_LAZY knob drives, kept as the
        differential oracle for the batched path."""

        async def scenario():
            h = Harness(
                Options(
                    inline_client=True, telemetry_sample=1,
                    fanout_batch=False,
                )
            )
            subs = []
            n = 4
            for i in range(n):
                r, w, _ = await h.connect(f"s{i}", version=5)
                w.write(
                    sub_packet(
                        1, [Subscription(filter="amp/t", qos=1)], version=5
                    )
                )
                await w.drain()
                assert (await read_wire_packet(r, 5)).fixed_header.type == SUBACK
                subs.append((r, w))
            pr, pw, _ = await h.connect("pub", version=5)
            pw.write(pub_packet("amp/t", b"x", qos=1, pid=9, version=5))
            await pw.drain()
            for r, _w in subs:
                pk = await read_wire_packet(r, 5)
                assert pk.topic_name == "amp/t"
                assert pk.fixed_header.qos == 1
            tele = h.server.telemetry
            block = tele.fanout_block(h.server.info.messages_received)
            assert block["publish_encodes"] == n
            assert block["fanout_deliveries"] == n
            assert block["fanout_variants"] == 0
            await h.shutdown()

        run(scenario())

    def test_qos0_frame_cache_encodes_once_per_variant(self):
        """QoS0 publish to N shareable v5 subscribers rides the frame
        cache: ONE encode per (version, retain) variant, N deliveries —
        the flat-amplification shape already achieved on this path."""

        async def scenario():
            h = Harness(Options(inline_client=True, telemetry_sample=1))
            subs = []
            n = 4
            for i in range(n):
                r, w, _ = await h.connect(f"s{i}", version=5)
                w.write(
                    sub_packet(
                        1, [Subscription(filter="amp/t", qos=0)], version=5
                    )
                )
                await w.drain()
                assert (await read_wire_packet(r, 5)).fixed_header.type == SUBACK
                subs.append((r, w))
            pr, pw, _ = await h.connect("pub", version=5)
            pw.write(pub_packet("amp/t", b"x", version=5))
            await pw.drain()
            for r, _w in subs:
                pk = await read_wire_packet(r, 5)
                assert pk.topic_name == "amp/t"
            tele = h.server.telemetry
            block = tele.fanout_block(h.server.info.messages_received)
            assert block["publish_encodes"] == 1
            assert block["fanout_deliveries"] == n
            assert block["encode_amplification"] == pytest.approx(1.0)
            assert block["delivery_amplification"] == pytest.approx(n)
            await h.shutdown()

        run(scenario())

    def test_v4_shared_frame_encodes_once(self):
        """The same fan-out with v4 subscribers rides the shared-frame
        fast path: deliveries == N but the frame is never re-encoded
        (encodes == 0 on the passthrough leg — the inbound bytes ARE the
        outbound bytes), which is exactly the flat-amplification shape
        ROADMAP item 3 wants from the decode path too."""

        async def scenario():
            h = Harness(Options(inline_client=True, telemetry_sample=1))
            subs = []
            n = 3
            for i in range(n):
                r, w, _ = await h.connect(f"s{i}", version=4)
                w.write(sub_packet(1, [Subscription(filter="amp/t", qos=0)]))
                await w.drain()
                assert (await read_wire_packet(r)).fixed_header.type == SUBACK
                subs.append((r, w))
            pr, pw, _ = await h.connect("pub", version=4)
            pw.write(pub_packet("amp/t", b"x"))
            await pw.drain()
            for r, _w in subs:
                pk = await read_wire_packet(r)
                assert pk.topic_name == "amp/t"
            tele = h.server.telemetry
            block = tele.fanout_block(h.server.info.messages_received)
            assert block["fanout_deliveries"] == n
            assert block["publish_encodes"] == 0
            assert block["delivery_amplification"] == pytest.approx(n)
            # per-client mirrors saw the writes
            total_writes = sum(
                cl.state.out_writes
                for cl in h.server.clients.get_all().values()
            )
            assert total_writes >= n
            await h.shutdown()

        run(scenario())

    def test_sys_fanout_excluded_from_amplification(self):
        """$SYS housekeeping republishes every interval with no inbound
        publish behind it — it must not count toward the encode/delivery
        amplification the ROADMAP item 3 gate watches."""

        async def scenario():
            h = Harness(Options(inline_client=True, telemetry_sample=1))
            r, w, _ = await h.connect("sys-watcher", version=4)
            w.write(sub_packet(1, [Subscription(filter="$SYS/#", qos=0)]))
            await w.drain()
            assert (await read_wire_packet(r)).fixed_header.type == SUBACK
            tele = h.server.telemetry
            before = (tele.publish_encodes.value, tele.fanout_deliveries.value)
            h.server.publish_sys_topics()
            # drain a few delivered $SYS publishes so the write loop ran
            for _ in range(3):
                pk = await read_wire_packet(r)
                assert pk.topic_name.startswith("$SYS")
            await asyncio.sleep(0)
            assert (
                tele.publish_encodes.value,
                tele.fanout_deliveries.value,
            ) == before
            await h.shutdown()

        run(scenario())

    def test_sketch_observes_sampled_topics(self):
        async def scenario():
            h = Harness(Options(inline_client=True, telemetry_sample=1))
            r, w, _ = await h.connect("s0", version=4)
            w.write(sub_packet(1, [Subscription(filter="sk/#", qos=0)]))
            await w.drain()
            assert (await read_wire_packet(r)).fixed_header.type == SUBACK
            pr, pw, _ = await h.connect("pub", version=4)
            for i in range(6):
                pw.write(pub_packet(f"sk/{i % 2}", b"x"))
            await pw.drain()
            for _ in range(6):
                await read_wire_packet(r)
            sk = h.server.topic_sketch
            assert sk is not None
            assert sk.total == 6
            tops = {d["topic"] for d in sk.top(4)}
            assert tops == {"sk/0", "sk/1"}
            await h.shutdown()

        run(scenario())


# -- HTTP matrix -------------------------------------------------------------


async def _http(host, port, path, method="GET"):
    reader, writer = await asyncio.open_connection(host, int(port))
    writer.write(f"{method} {path} HTTP/1.1\r\nHost: x\r\n\r\n".encode())
    await writer.drain()
    data = await asyncio.wait_for(reader.read(262144), TIMEOUT)
    writer.close()
    return data


class TestProfileHttpMatrix:
    def test_profile_matrix_and_formats(self):
        async def scenario():
            h = Harness(Options(inline_client=True))
            # make sure the profiler has at least one aggregated stack
            ev = threading.Event()

            def profile_http_probe_fn():
                ev.wait(TIMEOUT)

            t = threading.Thread(
                target=profile_http_probe_fn, daemon=True, name="probe"
            )
            t.start()
            await asyncio.sleep(0.02)
            h.server.host_profiler.sample_once()
            st = HTTPStats(
                LConfig(type="sysinfo", id="s", address="127.0.0.1:0"),
                h.server.info,
                telemetry=h.server.telemetry,
            )
            await st.init(h.server.log)
            host, port = st.address().rsplit(":", 1)
            # GET /profile: collapsed text, no-store
            data = await _http(host, port, "/profile")
            head, body = data.split(b"\r\n\r\n", 1)
            assert head.startswith(b"HTTP/1.1 200")
            assert b"Cache-Control: no-store" in head
            assert b"text/plain" in head
            assert check_collapsed(body.decode()) > 0
            assert b"profile_http_probe_fn" in body
            # trace format: Perfetto-loadable
            data = await _http(host, port, "/profile?format=trace")
            head, body = data.split(b"\r\n\r\n", 1)
            assert head.startswith(b"HTTP/1.1 200")
            assert b"application/json" in head
            assert check_trace_events(json.loads(body.decode())) > 0
            # non-GET on the KNOWN path: 405 with Allow
            post = await _http(host, port, "/profile", "POST")
            assert post.startswith(b"HTTP/1.1 405") and b"Allow: GET" in post
            ev.set()
            t.join(TIMEOUT)
            await st.close(lambda _: None)
            await h.shutdown()

        run(scenario())

    def test_profile_404_without_profiler(self):
        async def scenario():
            h = Harness(Options(inline_client=True, profile=False))
            assert h.server.host_profiler is None
            st = HTTPStats(
                LConfig(type="sysinfo", id="s", address="127.0.0.1:0"),
                h.server.info,
                telemetry=h.server.telemetry,
            )
            await st.init(h.server.log)
            host, port = st.address().rsplit(":", 1)
            assert (await _http(host, port, "/profile")).startswith(
                b"HTTP/1.1 404"
            )
            # 404 wins over 405 when the surface does not exist at all
            assert (await _http(host, port, "/profile", "POST")).startswith(
                b"HTTP/1.1 404"
            )
            await st.close(lambda _: None)
            await h.shutdown()

        run(scenario())

    def test_profile_404_without_telemetry(self):
        async def scenario():
            h = Harness(Options(inline_client=True, telemetry=False))
            st = HTTPStats(
                LConfig(type="sysinfo", id="s", address="127.0.0.1:0"),
                h.server.info,
                telemetry=h.server.telemetry,
            )
            await st.init(h.server.log)
            host, port = st.address().rsplit(":", 1)
            assert (await _http(host, port, "/profile")).startswith(
                b"HTTP/1.1 404"
            )
            await st.close(lambda _: None)
            await h.shutdown()

        run(scenario())


# -- lock metrics on /metrics ------------------------------------------------


class TestLockMetricsExposition:
    def test_lock_families_render_and_accumulate(self):
        async def scenario():
            from mqtt_tpu.telemetry import check_exposition

            h = Harness(Options(inline_client=True, telemetry_sample=1))
            plane = h.server.telemetry.lock_plane
            assert plane is not None
            plane.arm()  # Harness never serve()s, so arm explicitly
            try:
                r, w, _ = await h.connect("s0", version=4)
                w.write(sub_packet(1, [Subscription(filter="lm/#", qos=0)]))
                await w.drain()
                assert (await read_wire_packet(r)).fixed_header.type == SUBACK
                pr, pw, _ = await h.connect("pub", version=4)
                pw.write(pub_packet("lm/a", b"x"))
                await pw.drain()
                await read_wire_packet(r)
                text = h.server.telemetry.exposition()
                assert check_exposition(text) > 0
                assert 'mqtt_tpu_lock_wait_seconds_bucket{lock="clients"' in text
                assert 'mqtt_tpu_lock_hold_seconds_count{lock="clients"}' in text
                line = next(
                    l
                    for l in text.splitlines()
                    if l.startswith(
                        'mqtt_tpu_lock_acquisitions_total{lock="clients"}'
                    )
                )
                assert int(float(line.rsplit(" ", 1)[1])) > 0
            finally:
                plane.disarm()
            await h.shutdown()

        run(scenario())

    def test_trigger_dump_writes_profile_sibling(self, tmp_path):
        async def scenario():
            h = Harness(
                Options(
                    inline_client=True,
                    telemetry_dump_dir=str(tmp_path),
                    telemetry_dump_min_interval_ms=0.0,
                )
            )
            h.server.host_profiler.sample_once()
            h.server.telemetry.trigger_dump("test_reason")
            h.server.telemetry.recorder.join_writer()
            names = sorted(p.name for p in tmp_path.iterdir())
            assert any(n.startswith("flight_") for n in names), names
            profs = [n for n in names if n.startswith("profile_")]
            assert profs, names
            assert profs[0].endswith(".txt")
            await h.shutdown()

        run(scenario())
