"""Durable session plane (ISSUE 16): crash-safe log-structured storage
with snapshot + tail replay, seeded crash-point fault injection proving
recovery converges bit-identically from any kill point, batched restart
re-registration, the device-resident retained-match kernel with its
host-walk differential oracle and breaker degradation, and per-tenant
retained/subscription COUNT quotas refusing with v5 0x97.

The crash matrix drives the SAME seeded workload into every named crash
point (mid-append clean + torn, rotation, each snapshot and compaction
step) and asserts the recovered map equals the durable shadow — twice,
because recovery itself must be idempotent."""

import asyncio
import random
import types

import pytest

import mqtt_tpu.packets as pkts
from mqtt_tpu.faults import (
    STORAGE_CRASH_POINTS,
    StorageCrashPlan,
    dup_last_segment,
    lose_unsynced,
    tear_tail,
)
from mqtt_tpu.hooks.storage.logkv import (
    LogKVOptions,
    LogKVStore,
    SimulatedCrash,
)
from mqtt_tpu.packets import FixedHeader, Packet, Subscription
from mqtt_tpu.server import Options, Server
from mqtt_tpu.topics import TopicsIndex, ns_scope_filter, ns_scope_topic

from tests.test_server import (
    Harness,
    pub_packet,
    read_wire_packet,
    run,
    sub_packet,
)

PUBACK = 4
PUBLISH = 3
SUBACK = 9


# -- workload -------------------------------------------------------------


def _ops(seed, n):
    """A seeded set/del mix over a small hot key space (forces dead
    records, overwrites, and deletes into every segment)."""
    rng = random.Random(seed)
    ops = []
    for _ in range(n):
        k = f"CL_{rng.randrange(40)}"
        if rng.random() < 0.2:
            ops.append(("del", k, b""))
        else:
            ops.append(("set", k, bytes([rng.randrange(256)]) * rng.randrange(1, 24)))
    return ops


def _shadow_apply(shadow, kind, k, v):
    if kind == "set":
        shadow[k] = v
    else:
        shadow.pop(k, None)


def _reopen(path):
    s = LogKVStore()
    s.init(LogKVOptions(path=path, gc_interval=0))
    return s


class TestCrashPointMatrix:
    @pytest.mark.parametrize("point", STORAGE_CRASH_POINTS)
    def test_crash_point_converges(self, tmp_path, point):
        path = str(tmp_path / "kv")
        s = LogKVStore()
        s.init(
            LogKVOptions(
                path=path,
                gc_interval=0,
                durability_fsync="always",
                max_segment_bytes=512 if point == "rotate" else 1 << 20,
            )
        )
        shadow = {}
        crashed = False
        if point == "rotate":
            s.crash_plan = StorageCrashPlan(crash_point="rotate")
        for kind, k, v in _ops(1234, 300):
            try:
                if kind == "set":
                    s._set(k, v)
                else:
                    s._del(k)
            except SimulatedCrash:
                # the record that triggered rotation was written AND
                # fsynced before the crash point fired: it is durable
                crashed = True
                _shadow_apply(shadow, kind, k, v)
                break
            _shadow_apply(shadow, kind, k, v)
        if point.startswith("snapshot"):
            s.crash_plan = StorageCrashPlan(crash_point=point)
            with pytest.raises(SimulatedCrash):
                s.snapshot()
            crashed = True
        elif point.startswith("compact"):
            s.crash_plan = StorageCrashPlan(crash_point=point)
            with pytest.raises(SimulatedCrash):
                s.compact(0.0)
            crashed = True
        assert crashed, f"crash point {point} never fired"
        if s._file is not None:
            s._file.close()  # abandon: no clean stop() flush path

        s2 = _reopen(path)
        assert s2._map == shadow
        assert s2.replay_corruptions == 0
        s2.stop()
        # recovery must be idempotent: replaying the same files again
        # (including any overlap the crash left) reconverges
        s3 = _reopen(path)
        assert s3._map == shadow
        s3.stop()

    @pytest.mark.parametrize("torn", [False, True])
    @pytest.mark.parametrize("kill_at", [5, 57, 123])
    def test_crash_mid_append(self, tmp_path, torn, kill_at):
        """A kill mid-append (clean, or torn partial write) loses exactly
        the in-flight record; everything before it recovers."""
        path = str(tmp_path / "kv")
        s = LogKVStore()
        s.init(LogKVOptions(path=path, gc_interval=0, durability_fsync="always"))
        s.crash_plan = StorageCrashPlan(seed=kill_at, crash_at_op=kill_at, torn=torn)
        shadow = {}
        crashed = False
        for kind, k, v in _ops(99, 200):
            try:
                if kind == "set":
                    s._set(k, v)
                else:
                    s._del(k)
            except SimulatedCrash:
                crashed = True
                break  # the in-flight record never became durable
            _shadow_apply(shadow, kind, k, v)
        assert crashed
        if s._file is not None:
            s._file.close()
        s2 = _reopen(path)
        assert s2._map == shadow
        # a torn TAIL is a normal crash artifact, not corruption
        assert s2.replay_corruptions == 0
        s2.stop()

    def test_dup_segment_converges(self, tmp_path):
        """Replaying a duplicated newest segment is a no-op: records are
        absolute values, so recovery converges bit-identically."""
        path = str(tmp_path / "kv")
        s = LogKVStore()
        s.init(LogKVOptions(path=path, gc_interval=0))
        shadow = {}
        for kind, k, v in _ops(7, 150):
            if kind == "set":
                s._set(k, v)
            else:
                s._del(k)
            _shadow_apply(shadow, kind, k, v)
        s.stop()
        assert dup_last_segment(path)
        s2 = _reopen(path)
        assert s2._map == shadow
        assert s2.replay_corruptions == 0
        s2.stop()

    def test_tear_tail_recovers_a_prefix(self, tmp_path):
        """Tearing bytes off the newest segment recovers SOME prefix of
        the applied ops — never garbage, never a corruption count."""
        path = str(tmp_path / "kv")
        s = LogKVStore()
        s.init(LogKVOptions(path=path, gc_interval=0, durability_fsync="always"))
        states = [{}]
        for kind, k, v in _ops(41, 60):
            if kind == "set":
                s._set(k, v)
            else:
                s._del(k)
            nxt = dict(states[-1])
            _shadow_apply(nxt, kind, k, v)
            states.append(nxt)
        s.stop()
        assert tear_tail(path, seed=3)  # returns the torn segment's name
        s2 = _reopen(path)
        assert s2._map in states
        s2.stop()

    def test_lose_unsynced_rolls_back_to_watermark(self, tmp_path):
        """With fsync off, a power cut loses everything after the last
        explicit durability barrier — and nothing before it."""
        path = str(tmp_path / "kv")
        s = LogKVStore()
        s.init(LogKVOptions(path=path, gc_interval=0, durability_fsync="off"))
        for i in range(10):
            s._set(f"CL_a{i}", b"durable")
        s.sync()  # the barrier
        for i in range(10):
            s._set(f"CL_b{i}", b"volatile")
        lost = lose_unsynced(s)
        assert lost > 0
        s2 = _reopen(path)
        assert sorted(s2._map) == [f"CL_a{i}" for i in range(10)]
        s2.stop()


class TestSnapshotRecovery:
    def test_snapshot_plus_tail_replay(self, tmp_path):
        path = str(tmp_path / "kv")
        s = LogKVStore()
        s.init(LogKVOptions(path=path, gc_interval=0, max_segment_bytes=2048))
        shadow = {}
        for kind, k, v in _ops(11, 400):
            if kind == "set":
                s._set(k, v)
            else:
                s._del(k)
            _shadow_apply(shadow, kind, k, v)
        assert s.snapshot()
        tail_ops = 0
        for kind, k, v in _ops(12, 80):
            if kind == "set":
                s._set(k, v)
            else:
                s._del(k)
            _shadow_apply(shadow, kind, k, v)
            tail_ops += 1
        s.stop()

        s2 = _reopen(path)
        assert s2._map == shadow
        assert s2.snapshot_seq >= 0  # recovery used the snapshot
        # snapshot keys + tail records, NOT the full 400-op history —
        # that is the whole point of checkpointing
        assert s2.replayed_keys < 400 + tail_ops
        assert s2.durable_stats()["snapshot_age_seconds"] >= 0.0
        s2.stop()

    def test_fsync_policy_resolution(self):
        assert LogKVOptions(sync=True).fsync_policy() == "always"
        assert LogKVOptions(sync=False).fsync_policy() == "off"
        assert LogKVOptions(durability_fsync="batch").fsync_policy() == "batch"
        with pytest.raises(ValueError):
            LogKVOptions(durability_fsync="bogus").fsync_policy()

    def test_group_commit_batches_fsyncs(self, tmp_path):
        """The batch policy group-commits: one fsync covers many appends
        (vs. always = one fsync PER append)."""
        import time as _time

        path = str(tmp_path / "kv")
        s = LogKVStore()
        s.init(
            LogKVOptions(
                path=path,
                gc_interval=0,
                durability_fsync="batch",
                fsync_interval_ms=5.0,
            )
        )
        for i in range(200):
            s._set(f"CL_{i}", b"x" * 16)
        deadline = _time.monotonic() + 2.0
        while s._dirty and _time.monotonic() < deadline:
            _time.sleep(0.01)
        assert not s._dirty  # the flusher picked the batch up
        assert 0 < s.fsyncs < s.appends / 2
        s.stop()
        s2 = _reopen(path)
        assert len(s2._map) == 200
        s2.stop()

    @pytest.mark.slow
    def test_100k_key_recovery_bit_identical(self, tmp_path):
        """Fleet-shape leg: 100k+ keys recover bit-identically through a
        snapshot + tail, inside a sane time budget."""
        path = str(tmp_path / "kv")
        s = LogKVStore()
        s.init(
            LogKVOptions(path=path, gc_interval=0, max_segment_bytes=8 << 20)
        )
        shadow = {}
        for i in range(100_000):
            k, v = f"CL_{i}", b"v%d" % i
            s._set(k, v)
            shadow[k] = v
        assert s.snapshot()
        for i in range(0, 5000):  # tail updates after the checkpoint
            k, v = f"CL_{i}", b"w%d" % i
            s._set(k, v)
            shadow[k] = v
        s.stop()
        s2 = _reopen(path)
        assert len(s2._map) == 100_000
        assert s2._map == shadow
        assert s2.recovery_seconds < 30.0
        s2.stop()


# -- device-resident retained matching ------------------------------------


def _retain(idx, topic, payload=b"x"):
    pk = Packet(
        fixed_header=FixedHeader(type=PUBLISH, retain=True),
        topic_name=topic,
        payload=payload,
    )
    idx.retain_message(pk)


def _seed_retained_index():
    idx = TopicsIndex()
    topics = [
        "a",
        "a/b",
        "a/b/c",
        "x/y",
        "$SYS/broker/uptime",
        "$other/visible",
        ns_scope_topic("acme", "a/b"),
        ns_scope_topic("acme", "jobs/1"),
        ns_scope_topic("bulkco", "a/b"),
    ]
    for t in topics:
        _retain(idx, t)
    return idx, topics


FILTERS = [
    "a",
    "a/b",
    "#",
    "+",
    "a/#",
    "a/+",
    "+/b",
    "+/+",
    "$SYS/#",
    "$SYS/broker/+",
    "$other/#",
    "nope/+",
    ns_scope_filter("acme", "#"),
    ns_scope_filter("acme", "a/+"),
    ns_scope_filter("acme", "jobs/#"),
    ns_scope_filter("bulkco", "+/b"),
]


class TestRetainedMatchEngine:
    def test_bit_identical_vs_host_walk(self):
        from mqtt_tpu.ops.retained import RetainedMatchEngine

        idx, _ = _seed_retained_index()
        eng = RetainedMatchEngine(idx, oracle_sample=1)  # oracle EVERY call
        eng.reseed()
        for f in FILTERS:
            names = eng.match(f)
            host = sorted(p.topic_name for p in idx.messages(f))
            if names is not None:
                assert sorted(names) == host, f
        assert eng.oracle_mismatches == 0
        assert eng.device_matches > 0

    def test_deletion_tracked(self):
        from mqtt_tpu.ops.retained import RetainedMatchEngine

        idx, _ = _seed_retained_index()
        eng = RetainedMatchEngine(idx, oracle_sample=1)
        eng.reseed()
        assert "a/b" in (eng.match("a/+") or [])
        _retain(idx, "a/b", b"")  # clear
        eng.note_retained("a/b", False)
        names = eng.match("a/+")
        assert names is not None and "a/b" not in names
        assert eng.oracle_mismatches == 0

    def test_fault_storm_degrades_to_host(self, monkeypatch):
        """A failing kernel must degrade to the host walk through the
        breaker — never raise, never return wrong results."""
        import mqtt_tpu.ops.retained as retained_mod
        from mqtt_tpu.ops.retained import RetainedMatchEngine

        idx, _ = _seed_retained_index()
        eng = RetainedMatchEngine(idx, oracle_sample=1_000_000)
        eng.reseed()

        def boom(*a, **k):
            raise RuntimeError("device storm")

        monkeypatch.setattr(retained_mod, "flat_match_packed", boom)
        for _ in range(10):
            assert eng.match("a/+") is None  # host walk serves
        assert eng.breaker.state != "closed"
        assert eng.fallbacks["error"] >= 3
        assert eng.fallbacks["breaker"] >= 1

    def test_server_retained_delivery_with_engine(self):
        """Wire-level zero-missed-deliveries: retained messages reach a
        wildcard subscriber with the engine healthy AND mid-fault-storm
        (host degradation)."""

        async def scenario():
            h = Harness(Options(inline_client=False, retained_matcher=True))
            pr, pw, _ = await h.connect("rpub")
            pw.write(pub_packet("job/1", b"r1", retain=True))
            pw.write(pub_packet("job/2", b"r2", retain=True))
            await pw.drain()
            await asyncio.sleep(0.05)

            async def expect_retained(cid):
                sr, sw, _ = await h.connect(cid)
                sw.write(sub_packet(1, [Subscription(filter="job/+", qos=0)]))
                await sw.drain()
                got = set()
                for _ in range(3):
                    pk = await read_wire_packet(sr)
                    if pk.fixed_header.type == SUBACK:
                        continue
                    got.add((pk.topic_name, bytes(pk.payload)))
                assert got == {("job/1", b"r1"), ("job/2", b"r2")}

            await expect_retained("rsub-healthy")
            assert h.server._retained_engine.device_matches > 0

            # storm: every device call fails; delivery must not change
            def boom(*a, **k):
                raise RuntimeError("device storm")

            h.server._retained_engine._device_names = boom
            await expect_retained("rsub-storm")
            await h.shutdown()

        run(scenario())


# -- tenant count quotas ---------------------------------------------------


def quota_options(**kw):
    tenants = kw.pop("tenants", {"acme": {}})
    return Options(
        inline_client=False,
        tenancy=True,
        tenants=tenants,
        tenant_users={"cidA": "acme", "cidB": "acme"},
        **kw,
    )


class TestTenantCountQuotas:
    def test_subscription_cap_refuses_0x97(self):
        async def scenario():
            h = Harness(quota_options(tenant_max_subscriptions=2))
            r, w, _ = await h.connect("cidA", version=5)
            w.write(
                sub_packet(
                    1,
                    [
                        Subscription(filter="f/1", qos=0),
                        Subscription(filter="f/2", qos=0),
                        Subscription(filter="f/3", qos=0),
                    ],
                    version=5,
                )
            )
            await w.drain()
            ack = await read_wire_packet(r, 5)
            assert list(ack.reason_codes) == [0, 0, 0x97]
            t = h.server._tenancy.get("acme")
            assert t.subscriptions_count == 2
            assert t.subscriptions_refused == 1
            # replacing an existing filter is NOT growth
            w.write(sub_packet(2, [Subscription(filter="f/1", qos=0)], version=5))
            await w.drain()
            ack = await read_wire_packet(r, 5)
            assert list(ack.reason_codes) == [0]
            # unsubscribing frees the slot
            from mqtt_tpu.packets import encode_packet

            w.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=pkts.UNSUBSCRIBE, qos=1),
                        protocol_version=5,
                        packet_id=3,
                        filters=[Subscription(filter="f/2")],
                    )
                )
            )
            await w.drain()
            await read_wire_packet(r, 5)  # UNSUBACK
            assert t.subscriptions_count == 1
            w.write(sub_packet(4, [Subscription(filter="f/3", qos=0)], version=5))
            await w.drain()
            ack = await read_wire_packet(r, 5)
            assert list(ack.reason_codes) == [0]
            await h.shutdown()

        run(scenario())

    def test_subscription_cap_clamps_for_v3(self):
        async def scenario():
            h = Harness(quota_options(tenant_max_subscriptions=1))
            r, w, _ = await h.connect("cidA", version=4)
            w.write(
                sub_packet(
                    1,
                    [
                        Subscription(filter="f/1", qos=0),
                        Subscription(filter="f/2", qos=0),
                    ],
                )
            )
            await w.drain()
            ack = await read_wire_packet(r)
            assert list(ack.reason_codes) == [0, 0x80]  # v3: no 0x97
            await h.shutdown()

        run(scenario())

    def test_retained_cap_refuses_0x97(self):
        async def scenario():
            h = Harness(quota_options(tenant_max_retained=2))
            r, w, _ = await h.connect("cidA", version=5)
            for pid, topic in ((1, "r/1"), (2, "r/2")):
                w.write(pub_packet(topic, b"x", qos=1, pid=pid, version=5, retain=True))
                await w.drain()
                ack = await read_wire_packet(r, 5)
                assert ack.fixed_header.type == PUBACK and ack.reason_code == 0
            t = h.server._tenancy.get("acme")
            assert t.retained_count == 2
            # the third NEW retained topic refuses 0x97
            w.write(pub_packet("r/3", b"x", qos=1, pid=3, version=5, retain=True))
            await w.drain()
            ack = await read_wire_packet(r, 5)
            assert ack.reason_code == 0x97
            assert t.retained_refused == 1
            assert t.retained_count == 2  # memory did not grow past cap
            # overwriting an existing retained topic always passes
            w.write(pub_packet("r/1", b"y", qos=1, pid=4, version=5, retain=True))
            await w.drain()
            ack = await read_wire_packet(r, 5)
            assert ack.reason_code == 0
            # clearing frees a slot; the refused topic then fits
            w.write(pub_packet("r/1", b"", qos=1, pid=5, version=5, retain=True))
            await w.drain()
            ack = await read_wire_packet(r, 5)
            assert ack.reason_code == 0
            assert t.retained_count == 1
            w.write(pub_packet("r/3", b"x", qos=1, pid=6, version=5, retain=True))
            await w.drain()
            ack = await read_wire_packet(r, 5)
            assert ack.reason_code == 0
            assert t.retained_count == 2
            await h.shutdown()

        run(scenario())

    def test_retained_cap_qos0_drops_counted(self):
        async def scenario():
            h = Harness(quota_options(tenant_max_retained=1))
            r, w, _ = await h.connect("cidA", version=5)
            w.write(pub_packet("r/1", b"x", qos=1, pid=1, version=5, retain=True))
            await w.drain()
            await read_wire_packet(r, 5)
            dropped = h.server.info.messages_dropped
            w.write(pub_packet("r/2", b"x", version=5, retain=True))  # qos0
            await w.drain()
            await asyncio.sleep(0.05)
            t = h.server._tenancy.get("acme")
            assert t.retained_refused == 1
            assert h.server.info.messages_dropped == dropped + 1
            assert t.retained_count == 1
            await h.shutdown()

        run(scenario())

    def test_per_tenant_override_beats_default(self):
        async def scenario():
            h = Harness(
                quota_options(
                    tenants={"acme": {"max_retained": 1}},
                    tenant_max_retained=5,
                )
            )
            r, w, _ = await h.connect("cidA", version=5)
            w.write(pub_packet("r/1", b"x", qos=1, pid=1, version=5, retain=True))
            await w.drain()
            assert (await read_wire_packet(r, 5)).reason_code == 0
            w.write(pub_packet("r/2", b"x", qos=1, pid=2, version=5, retain=True))
            await w.drain()
            assert (await read_wire_packet(r, 5)).reason_code == 0x97
            await h.shutdown()

        run(scenario())


# -- batched restart re-registration / recovery plumbing -------------------


class TestBatchedRestore:
    def test_load_subscriptions_flows_in_bulk(self):
        srv = Server(Options(inline_client=False, durable_restore_batch=8))
        subs = [
            types.SimpleNamespace(
                client=f"c{i}",
                filter=f"t/{i}",
                qos=1,
                retain_handling=0,
                retain_as_published=False,
                no_local=False,
                identifier=0,
                predicates=(),
            )
            for i in range(20)
        ]
        batches = []
        orig = srv.topics.subscribe_bulk
        srv.topics.subscribe_bulk = lambda entries: (
            batches.append(len(entries)),
            orig(entries),
        )[1]
        srv.load_subscriptions(subs)
        assert batches == [8, 8, 4]  # chunked, NOT one-at-a-time
        assert srv._durable["restored_subscriptions"] == 20
        assert srv._durable["restore_batches"] == 3
        # the trie actually holds them
        assert not srv.topics.subscribe("c3", Subscription(filter="t/3", qos=1))

    def test_load_retained_bulk_and_engine_seed(self):
        srv = Server(Options(inline_client=False, retained_matcher=True))

        def stored(topic):
            return types.SimpleNamespace(
                to_packet=lambda t=topic: Packet(
                    fixed_header=FixedHeader(type=PUBLISH, retain=True),
                    topic_name=t,
                    payload=b"x",
                )
            )

        srv.load_retained([stored(f"r/{i}") for i in range(10)])
        assert srv._durable["restored_retained"] == 10
        assert len(srv.topics.retained) == 10
        names = srv._retained_engine.match("r/+")
        assert names is not None and len(names) == 10

    def test_healthz_holds_503_while_recovering(self):
        srv = Server(Options(inline_client=False))
        srv._durable["recovering"] = True
        ok, detail = srv.health_report()
        assert not ok and "recovering" in detail["not_ready"]
        srv._durable["recovering"] = False
        ok, detail = srv.health_report()
        assert ok and "recovering" not in detail["not_ready"]

    def test_unacked_inflight_survives_kill9(self, tmp_path):
        """The QoS1 unacked window rides the batched restore path: a
        subscriber that never PUBACKs is killed along with the broker
        (the store directory is frozen mid-flight, exactly what a
        kill -9 leaves on disk), and the next life re-inflates the
        window through ``staging.bulk_inflight`` — counted, batched,
        and live in the session's inflight map."""
        import shutil

        path = str(tmp_path / "kv")
        crash = str(tmp_path / "kv-crash-image")

        async def first_life():
            h = Harness(Options(inline_client=False))
            store = LogKVStore()
            h.server.add_hook(store, LogKVOptions(path=path, gc_interval=0))
            r, w, _ = await h.connect("keeper", version=4, clean=False)
            w.write(sub_packet(1, [Subscription(filter="dur/+", qos=1)]))
            await w.drain()
            await read_wire_packet(r)
            rp, wp, _ = await h.connect("pusher", version=4)
            wp.write(pub_packet("dur/q", b"unacked", qos=1, pid=9))
            await wp.drain()
            assert (await read_wire_packet(rp)).fixed_header.type == PUBACK
            # the delivery reaches the wire (on_qos_publish persisted
            # the window entry)... and is never acknowledged
            fwd = await read_wire_packet(r)
            assert fwd.fixed_header.type == PUBLISH
            assert bytes(fwd.payload) == b"unacked"
            store.sync()  # the fsync the group-commit loop would do
            # kill -9: freeze the on-disk state at this instant; the
            # clean teardown below never touches the crash image
            shutil.copytree(path, crash)
            await h.shutdown()
            store.stop()

        run(first_life())

        async def second_life():
            h = Harness(Options(inline_client=False))
            h.server.add_hook(
                LogKVStore(), LogKVOptions(path=crash, gc_interval=0)
            )
            h.server.read_store()
            srv = h.server
            assert srv._durable["restored_inflight"] == 1
            assert srv._durable["restore_batches"] >= 1
            cl = srv.clients.get("keeper")
            assert cl is not None
            # the window is LIVE: the restored packet is queued for
            # resend under its original packet id
            assert len(cl.state.inflight) == 1
            pk = cl.state.inflight.get_all(False)[0]
            assert bytes(pk.payload) == b"unacked"
            srv.publish_durable_sys()
            row = srv.topics.retained.get(
                "$SYS/broker/durable/restored_inflight"
            )
            assert row is not None and int(row.payload) == 1
            await h.shutdown()

        run(second_life())

    def test_restart_restores_through_logkv(self, tmp_path):
        """End-to-end in-process restart: sessions + retained topics
        persisted through the LogKV store come back bit-identical, the
        recovery counters populate, and $SYS/broker/durable rows exist."""
        path = str(tmp_path / "kv")

        async def first_life():
            h = Harness(Options(inline_client=False))
            store = LogKVStore()
            h.server.add_hook(store, LogKVOptions(path=path, gc_interval=0))
            # v4 clean=False: the session persists across disconnects
            r, w, _ = await h.connect("keeper", version=4, clean=False)
            w.write(
                sub_packet(
                    1,
                    [
                        Subscription(filter="dur/+", qos=1),
                        Subscription(filter="other/#", qos=0),
                    ],
                )
            )
            await w.drain()
            await read_wire_packet(r)
            w.write(pub_packet("dur/ret", b"keepme", retain=True))
            await w.drain()
            await asyncio.sleep(0.05)
            await h.shutdown()
            store.stop()  # the clean-shutdown flush the broker would do

        run(first_life())

        async def second_life():
            h = Harness(Options(inline_client=False))
            h.server.add_hook(
                LogKVStore(), LogKVOptions(path=path, gc_interval=0)
            )
            h.server.read_store()
            srv = h.server
            assert srv._durable["recovering"]  # serve() clears it
            assert srv._durable["replayed_keys"] > 0
            assert srv._durable["restored_subscriptions"] == 2
            assert srv._durable["restored_retained"] == 1
            assert srv._durable["recovery_seconds"] > 0.0
            # the restored subscription is live in the trie
            assert not srv.topics.subscribe(
                "keeper", Subscription(filter="dur/+", qos=1)
            )
            ret = srv.topics.retained.get("dur/ret")
            assert ret is not None and bytes(ret.payload) == b"keepme"
            ok, detail = srv.health_report()
            assert not ok and "recovering" in detail["not_ready"]
            assert detail["durable"]["replayed_keys"] > 0
            # what serve() does once listeners are up
            srv._durable["recovering"] = False
            srv.publish_durable_sys()
            row = srv.topics.retained.get("$SYS/broker/durable/replayed_keys")
            assert row is not None and int(row.payload) > 0
            await h.shutdown()

        run(second_life())
