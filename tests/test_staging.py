"""The publish staging loop: device_matcher=True end-to-end through the
real broker (SURVEY.md §7 stage 4; round-3 VERDICT item 2).

Covers: >=100 concurrent publishers fanning out through batched device
matches with correct per-subscriber delivery, proof that matching was
batched (not one device round trip per publish on the event loop), QoS1
ack-before-fan-out ordering, $SYS/broker/matcher observability topics,
and stage shutdown draining via the host walk.
"""

import asyncio

import pytest

from mqtt_tpu import Options, Server
from mqtt_tpu.packets import PUBLISH, SUBACK, FixedHeader, Packet, Subscription
from mqtt_tpu.staging import MatchStage
from mqtt_tpu.topics import SYS_PREFIX, Subscribers

from tests.test_server import (
    Harness,
    pub_packet,
    read_wire_packet,
    run,
    sub_packet,
)

N_PUBLISHERS = 100
MSGS_EACH = 2


def staged_options(**kw):
    return Options(
        inline_client=True,
        device_matcher=True,
        # tight window keeps the test fast while still coalescing the
        # concurrent publishers into real batches
        matcher_stage_window_ms=kw.pop("window_ms", 5.0),
        matcher_opts={"max_levels": 4, "background": False},
        **kw,
    )


class TestStagedBroker:
    def test_hundred_concurrent_publishers_fan_out(self):
        async def scenario():
            h = Harness(staged_options())
            await h.server.serve()  # starts the stage (no listeners bound)
            assert h.server._stage is not None

            # one wildcard subscriber + one exact subscriber
            sub_r, sub_w, _ = await h.connect("sub-wild")
            sub_w.write(sub_packet(1, [Subscription(filter="t/#", qos=0)]))
            await sub_w.drain()
            assert (await read_wire_packet(sub_r)).fixed_header.type == SUBACK
            sub2_r, sub2_w, _ = await h.connect("sub-exact")
            sub2_w.write(sub_packet(1, [Subscription(filter="t/p7/x", qos=0)]))
            await sub2_w.drain()
            assert (await read_wire_packet(sub2_r)).fixed_header.type == SUBACK

            # fold the subscription overlay so the device index (not the
            # host overlay route) serves the publish matches
            h.server.matcher.flush()

            pubs = []
            for i in range(N_PUBLISHERS):
                r, w, _ = await h.connect(f"pub{i}")
                pubs.append((r, w))

            async def publish_all(i, w):
                for m in range(MSGS_EACH):
                    w.write(pub_packet(f"t/p{i}/x", f"m{i}-{m}".encode()))
                    await w.drain()

            await asyncio.gather(*(publish_all(i, w) for i, (_, w) in enumerate(pubs)))

            # the wildcard subscriber receives every message
            got = set()
            for _ in range(N_PUBLISHERS * MSGS_EACH):
                pk = await read_wire_packet(sub_r)
                assert pk.fixed_header.type == PUBLISH
                got.add((pk.topic_name, bytes(pk.payload)))
            assert len(got) == N_PUBLISHERS * MSGS_EACH
            # the exact subscriber receives only its topic, in order
            for m in range(MSGS_EACH):
                pk = await read_wire_packet(sub2_r)
                assert pk.topic_name == "t/p7/x"
                assert bytes(pk.payload) == f"m7-{m}".encode()

            # matching really was batched: far fewer device batches than
            # published messages (no per-publish round trip on the loop)
            stats = h.server.matcher.stats
            assert stats.topics >= N_PUBLISHERS * MSGS_EACH
            assert stats.batches < stats.topics / 2, (
                f"batches={stats.batches} topics={stats.topics}: staging "
                "did not coalesce"
            )
            # the folded index really served from the device: the publish
            # topics matched post-flush must not all have host-routed
            assert stats.host_fallbacks < stats.topics, stats.as_dict()

            await h.server.close()
            await h.shutdown()

        run(scenario())

    def test_qos1_ack_precedes_fan_out_and_sys_topics(self):
        async def scenario():
            h = Harness(staged_options())
            await h.server.serve()

            sub_r, sub_w, _ = await h.connect("sub")
            sub_w.write(sub_packet(1, [Subscription(filter="q/+", qos=1)]))
            await sub_w.drain()
            await read_wire_packet(sub_r)

            pub_r, pub_w, _ = await h.connect("pub")
            pub_w.write(pub_packet("q/1", b"hello", qos=1, pid=9))
            await pub_w.drain()
            ack = await read_wire_packet(pub_r)  # PUBACK written sync
            assert ack.packet_id == 9
            out = await read_wire_packet(sub_r)
            assert out.topic_name == "q/1" and bytes(out.payload) == b"hello"

            # $SYS matcher observability (round-3 VERDICT item 2 tail)
            h.server.publish_sys_topics()
            retained = h.server.topics.retained
            batches = retained.get(SYS_PREFIX + "/broker/matcher/batches")
            assert batches is not None and int(batches.payload) >= 1
            assert retained.get(SYS_PREFIX + "/broker/matcher/fallback_ratio") is not None

            await h.server.close()
            await h.shutdown()

        run(scenario())


class TestMatchStageUnit:
    def test_stage_error_falls_back_to_host(self):
        class BoomMatcher:
            def match_topics_async(self, topics):
                raise RuntimeError("boom")

        async def scenario():
            hits = []

            def host(topic):
                hits.append(topic)
                return Subscribers()

            stage = MatchStage(BoomMatcher(), host, window_s=0.001)
            stage.start()
            subs = await stage.submit("a/b")
            assert isinstance(subs, Subscribers)
            assert hits == ["a/b"]
            await stage.stop()

        run(scenario())

    def test_stage_stop_drains_pending_via_host(self):
        class NeverMatcher:
            def match_topics_async(self, topics):
                def resolve():
                    raise RuntimeError("resolver exploded")

                return resolve

        async def scenario():
            stage = MatchStage(
                NeverMatcher(), lambda t: Subscribers(), window_s=0.001
            )
            stage.start()
            fut = stage.submit("x/y")
            subs = await asyncio.wait_for(fut, 5)
            assert isinstance(subs, Subscribers)
            await stage.stop()
            # post-stop submissions resolve immediately via the host walk
            fut2 = stage.submit("x/z")
            assert fut2.done()

        run(scenario())


class TestCancelledCallerFutures:
    def test_cancelled_mid_window_leaks_nothing(self):
        """A client disconnecting during the accumulation window cancels
        its staged futures: the collector must prune them (no device
        work for dead callers), the drainer and _fallback_all must not
        raise InvalidStateError, and nothing leaks in _pending/_queue."""

        import threading

        class GatedMatcher:
            def __init__(self):
                self.calls = []
                self.release = threading.Event()

            def match_topics_async(self, topics):
                self.calls.append(list(topics))

                def resolve():
                    self.release.wait(5)
                    return [Subscribers() for _ in topics]

                return resolve

        async def scenario():
            m = GatedMatcher()
            stage = MatchStage(
                m, lambda t: Subscribers(), window_s=0.05, max_inflight=2
            )
            stage.start()
            futs = [stage.submit(f"c/{i}") for i in range(6)]
            for f in futs[:3]:
                f.cancel()  # disconnect during the window
            await asyncio.sleep(0.1)  # window elapses, batch dispatches
            assert m.calls and len(m.calls[0]) == 3  # cancelled pruned
            m.release.set()
            results = await asyncio.gather(*futs[3:])
            assert all(isinstance(r, Subscribers) for r in results)
            assert stage._pending == []

            # cancel AFTER dispatch (in-flight): the drainer must skip
            # the cancelled future without InvalidStateError
            m.release.clear()
            late = stage.submit("c/late")
            await asyncio.sleep(0.08)  # dispatched, resolver gated
            late.cancel()
            m.release.set()
            await asyncio.sleep(0.1)
            assert stage._queue.empty()
            await stage.stop()

        run(scenario())

    def test_stop_with_cancelled_pending_is_clean(self):
        """_fallback_all over a mix of live and cancelled futures: the
        cancelled ones are skipped (no InvalidStateError), the live ones
        resolve via the host walk."""

        async def scenario():
            stage = MatchStage(None, lambda t: Subscribers())
            stage._wake = asyncio.Event()  # park without a collector
            futs = [stage.submit(f"x/{i}") for i in range(4)]
            futs[0].cancel()
            futs[2].cancel()
            await stage.stop()
            assert futs[1].done() and futs[3].done()
            assert isinstance(futs[1].result(), Subscribers)
            assert isinstance(futs[3].result(), Subscribers)

        run(scenario())


class TestCrossLoopResolution:
    def test_fallback_rejection_marshals_to_submitter_loop(self):
        """Regression for the brokerlint R12 finding fixed in PR 19
        (staging._reject): ``_fallback_all`` used to call
        ``fut.set_exception`` INLINE on whatever thread ran the
        fallback, scheduling the waiter's done-callbacks cross-thread.
        The submitter loop runs in DEBUG mode here, so the old inline
        shape trips asyncio's non-thread-safe-operation check and the
        test fails loudly if the marshal seam regresses."""
        import threading

        class Boom(Exception):
            pass

        def exploding_host(topic):
            raise Boom(topic)

        loop_b = asyncio.new_event_loop()
        loop_b.set_debug(True)
        t = threading.Thread(
            target=loop_b.run_forever, name="submitter-loop", daemon=True
        )
        t.start()
        stage_loop = asyncio.new_event_loop()  # never running: just != loop_b
        try:

            async def park():
                return asyncio.get_running_loop().create_future()

            fut = asyncio.run_coroutine_threadsafe(park(), loop_b).result(5)
            rej = MatchStage(None, exploding_host)
            rej._loop = stage_loop
            # the old code raises RuntimeError (non-thread-safe op) here
            rej._fallback_all([("x/y", fut)])

            async def reap():
                try:
                    await fut
                except Boom:
                    return threading.get_ident()
                raise AssertionError("future resolved without the host error")

            # the rejection completed ON the submitter's loop thread
            assert (
                asyncio.run_coroutine_threadsafe(reap(), loop_b).result(5)
                == t.ident
            )

            # the success leg rides the same seam (_resolve's marshal)
            fut2 = asyncio.run_coroutine_threadsafe(park(), loop_b).result(5)
            ok = MatchStage(None, lambda t: Subscribers())
            ok._loop = stage_loop
            ok._fallback_all([("x/z", fut2)])

            async def reap_ok():
                return await fut2

            assert isinstance(
                asyncio.run_coroutine_threadsafe(reap_ok(), loop_b).result(5),
                Subscribers,
            )
        finally:
            loop_b.call_soon_threadsafe(loop_b.stop)
            t.join(5)
            loop_b.close()
            stage_loop.close()

    def test_inject_packet_tracks_fan_out_task(self):
        """Regression for the brokerlint R13 finding fixed in PR 19
        (server.inject_packet): the staged fan-out task was
        fire-and-forget — asyncio's weak reference was the only thing
        keeping it alive mid-flight. It must register in the tracked
        listener task set and discard itself on completion."""

        async def scenario():
            h = Harness(staged_options())
            await h.server.serve()
            sub_r, sub_w, _ = await h.connect("inj-sub")
            sub_w.write(sub_packet(1, [Subscription(filter="in/t", qos=0)]))
            await sub_w.drain()
            await read_wire_packet(sub_r)
            h.server.matcher.flush()
            cl = h.server.clients.get("inj-sub")
            before = set(h.server.listeners.client_tasks)
            h.server.inject_packet(
                cl,
                Packet(
                    fixed_header=FixedHeader(type=PUBLISH),
                    topic_name="in/t",
                    payload=b"injected",
                ),
            )
            spawned = set(h.server.listeners.client_tasks) - before
            assert len(spawned) == 1, "staged fan-out task must be tracked"
            pk = await read_wire_packet(sub_r)
            assert bytes(pk.payload) == b"injected"
            task = spawned.pop()
            await task
            await asyncio.sleep(0)  # let the done-callback run
            assert task not in h.server.listeners.client_tasks
            await h.server.close()
            await h.shutdown()

        run(scenario())


class TestAdaptiveWindow:
    def test_window_headroom_scales_with_queue_depth(self):
        """Regression (ADVICE r5): _observe_service budgets depth x
        service, so _window must too — with a deep queue the pipeline can
        be over budget while one batch's service is not, and the
        collector must stop adding window sleep on top."""

        async def scenario():
            stage = MatchStage(
                None,
                lambda t: Subscribers(),
                window_s=0.01,
                latency_budget_s=0.1,
            )
            stage._ewma_s = 0.04  # one batch: comfortably under budget
            assert stage._window() > 0.0  # no queue yet: depth 1
            stage._queue = asyncio.Queue(maxsize=8)
            for _ in range(3):
                stage._queue.put_nowait(None)
            # effective latency = depth(4) x 0.04 = 0.16 > 0.1 budget:
            # the window collapses instead of sleeping on top of it
            assert stage._window() == 0.0
            stage._queue.get_nowait()
            stage._queue.get_nowait()
            stage._queue.get_nowait()
            # depth 1 x 0.04 leaves headroom again
            assert stage._window() > 0.0

        run(scenario())


class TestSingleConnectionPipelining:
    def test_one_client_burst_coalesces(self):
        """All publishes in one socket write must reach the stage before
        the read loop blocks on any of them (clients.py scan batching)."""

        async def scenario():
            h = Harness(staged_options())
            await h.server.serve()
            sub_r, sub_w, _ = await h.connect("sub")
            sub_w.write(sub_packet(1, [Subscription(filter="b/#", qos=0)]))
            await sub_w.drain()
            await read_wire_packet(sub_r)
            h.server.matcher.flush()

            pub_r, pub_w, _ = await h.connect("pub")
            burst = b"".join(
                pub_packet(f"b/{i}", f"x{i}".encode()) for i in range(50)
            )
            pub_w.write(burst)  # ONE socket write, 50 publishes
            await pub_w.drain()

            for i in range(50):
                pk = await read_wire_packet(sub_r)
                assert pk.topic_name == f"b/{i}"  # order preserved

            stats = h.server.matcher.stats
            assert stats.batches <= 5, stats.as_dict()  # coalesced, not 50
            await h.server.close()
            await h.shutdown()

        run(scenario())
