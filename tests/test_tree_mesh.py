"""Spanning-tree mesh suite (ISSUE 9): the in-process end of the
interest-scoped tree fabric — deterministic election and O(degree)
links, multi-hop summary-gated routing, the per-edge health machine
(sever -> scoped re-election -> exactly-once heal under the new epoch),
duplicate suppression, and the per-signal pressure-gossip fold.

The 32-worker subprocess drill lives in tests/test_mesh_drill.py (slow,
nightly); this file is the tier-1 correctness net over the same
machinery at 5 workers, where every worker is a full in-process Server.
"""

import asyncio
import json
import struct
import time

import pytest

from mqtt_tpu.cluster import (
    _T_RFRAME,
    PEER_SUSPECT,
    PEER_UP,
    Cluster,
)
from mqtt_tpu.faults import asymmetric_partition, sever_peer_link
from mqtt_tpu.mesh_topology import compute_parents, tree_neighbors
from mqtt_tpu.overload import PeerPressureSignal
from mqtt_tpu.packets import PUBACK, PUBLISH, Subscription
from mqtt_tpu.server import Options

from tests.test_server import (
    Harness,
    pub_packet,
    read_wire_packet,
    sub_packet,
)


def run(coro, timeout=60):
    """Local runner with headroom for partition/backoff legs (the
    test_server default of 15s is tuned for single-broker scenarios)."""
    return asyncio.run(asyncio.wait_for(coro, timeout=timeout))


async def wait_for(cond, timeout=20.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        await asyncio.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


DEGREE = 2


class TreeMesh:
    """N in-process workers in tree mode, with the drill-grade fast
    clocks: 0.1s ping/gossip cadence and millisecond dial backoff."""

    def __init__(self, n, tmp_path, degree=DEGREE, partition_pings=0, **opt_kw):
        self.n = n
        self.harnesses = [
            Harness(
                Options(
                    inline_client=True,
                    cluster_topology="tree",
                    cluster_tree_degree=degree,
                    **opt_kw,
                )
            )
            for _ in range(n)
        ]
        self.clusters = [
            Cluster(h.server, i, n, str(tmp_path))
            for i, h in enumerate(self.harnesses)
        ]
        for c in self.clusters:
            c.PING_INTERVAL_S = 0.1
            c.DIAL_BACKOFF_S = 0.02
            c.DIAL_BACKOFF_MAX_S = 0.2
            c.PROBE_BACKOFF_S = 0.1
            if partition_pings:
                # tests that must OBSERVE the SUSPECT park before the
                # partition verdict widen the window: at the 0.1s drill
                # cadence the default 5-ping threshold is only 0.5s and
                # a loaded CI host can blow through it
                c.partition_pings = partition_pings

    async def start(self):
        for h in self.harnesses:
            await h.server.serve()
        for c in self.clusters:
            await c.start()
        await wait_for(
            lambda: all(
                all(p in c._writers for p in c.topo.neighbors())
                for c in self.clusters
            ),
            msg="tree links up",
        )

    async def stop(self, skip=()):
        for c in self.clusters:
            if c.worker_id not in skip:
                await c.stop()
        for h in self.harnesses:
            await h.server.close()
            await h.shutdown()

    async def subscribe(self, worker, client_id, filter, qos=1):
        r, w, _ = await self.harnesses[worker].connect(client_id, version=4)
        w.write(sub_packet(1, [Subscription(filter=filter, qos=qos)], version=4))
        ack = await read_wire_packet(r, 4)
        assert ack.fixed_header.type != PUBLISH or True
        return r, w

    async def settle_summaries(self):
        """Wait until every edge's interest summary is stamped with the
        receiver's CURRENT epoch (the summary gate is live, not in
        conservative pass-through)."""
        def _epoch_key(c):
            ep = c.topo.epoch
            return (ep.num, ep.boot, ep.proposer)

        await wait_for(
            lambda: all(
                all(
                    p in c._edge_summaries
                    and c._edge_summaries[p].ep_key == _epoch_key(c)
                    for p in c.topo.neighbors()
                )
                for c in self.clusters
            ),
            msg="summaries settled",
        )


async def read_until_payload(reader, payload, version=4, timeout=10):
    """Read PUBLISHes until ``payload`` arrives; returns all payloads
    seen (duplicate accounting reads the full list)."""
    seen = []

    async def inner():
        while True:
            pk = await read_wire_packet(reader, version)
            if pk.fixed_header.type != PUBLISH:
                continue
            seen.append(bytes(pk.payload))
            if pk.payload == payload:
                return

    await asyncio.wait_for(inner(), timeout)
    return seen


# -- election + links ---------------------------------------------------------


class TestTreeBoot:
    def test_links_stay_o_degree_and_match_the_computed_tree(self, tmp_path):
        async def scenario():
            mesh = TreeMesh(5, tmp_path)
            await mesh.start()
            parents = compute_parents(range(5), DEGREE)
            for c in mesh.clusters:
                assert set(c.topo.neighbors()) == set(
                    tree_neighbors(parents, c.worker_id)
                )
                # the O(degree) bound: parent + children, nothing else
                assert len(c._writers) <= DEGREE + 1
                assert set(c._writers) <= set(c.topo.neighbors())
            await mesh.stop()

        run(scenario())

    def test_knob_normalization(self):
        o = Options(
            cluster_topology="RING",
            cluster_tree_degree=0,
            cluster_summary_bits=7,
            cluster_dup_window=-1,
        )
        o.ensure_defaults()
        assert o.cluster_topology == "mesh"  # unknown mode: safe fallback
        assert o.cluster_tree_degree == 4
        assert o.cluster_summary_bits == 4096
        assert o.cluster_dup_window == 8192
        o2 = Options(cluster_topology="Tree")
        o2.ensure_defaults()
        assert o2.cluster_topology == "tree"

    def test_config_file_passthrough(self):
        from mqtt_tpu.config import from_bytes

        opts = from_bytes(
            b"""
options:
  cluster_topology: tree
  cluster_tree_degree: 3
  cluster_summary_bits: 8192
  cluster_dup_window: 1024
"""
        )
        assert opts.cluster_topology == "tree"
        assert opts.cluster_tree_degree == 3
        assert opts.cluster_summary_bits == 8192
        assert opts.cluster_dup_window == 1024

    def test_epoch_digest_reconciles_divergence(self, tmp_path):
        """The anti-entropy heartbeat is a 3-int digest: agreement costs
        nothing, disagreement is answered with the full member map, and
        a digest alone can never move the tree (adoption needs the map).
        End to end, a divergent pair reconciles off one digest."""

        async def scenario():
            mesh = TreeMesh(3, tmp_path)
            await mesh.start()
            c0, c1 = mesh.clusters[0], mesh.clusters[1]
            ep = c0.topo.epoch
            calls = []
            real = c0._announce_epoch
            c0._announce_epoch = lambda only=None, digest=False: calls.append(
                (tuple(only or ()), digest)
            )
            try:
                agree = json.dumps({"e": [ep.num, ep.boot, ep.proposer]})
                c0._on_epoch(1, agree.encode())
                assert not calls  # agreement is free
                ahead = json.dumps({"e": [ep.num + 5, ep.boot, ep.proposer]})
                c0._on_epoch(1, ahead.encode())
                assert calls == [((1,), False)]  # answered with the map
                assert c0.topo.epoch == ep  # the digest moved nothing
            finally:
                c0._announce_epoch = real
            # e2e: worker 1 re-elects without worker 2; its next digest
            # heartbeat makes 0 answer back, 1 answers with its map, 0
            # adopts — full convergence off a 3-int frame
            assert c1.topo.propose_remove(2) is not None
            assert c1.topo.epoch > c0.topo.epoch
            await wait_for(
                lambda: c0.topo.epoch == c1.topo.epoch, msg="digest heal"
            )
            await mesh.stop()

        run(scenario())

    def test_worker_env_round_trip(self, tmp_path):
        from mqtt_tpu.cluster import worker_env

        env = worker_env(3, 8, str(tmp_path), topology="tree", degree=3)
        assert env["MQTT_TPU_CLUSTER_TOPOLOGY"] == "tree"
        assert env["MQTT_TPU_CLUSTER_DEGREE"] == "3"
        # mesh mode (the default) sets neither: every worker must agree
        assert "MQTT_TPU_CLUSTER_TOPOLOGY" not in worker_env(0, 2, "x")


# -- routing ------------------------------------------------------------------


class TestTreeRouting:
    def test_multi_hop_qos0_and_qos1(self, tmp_path):
        """Leaf-to-leaf delivery crosses two interior hops (2 -> 0 -> 1
        -> 4 at degree 2): the passthrough frame is re-forwarded at each
        hop under the frame's epoch, and QoS1 rides the packet path."""

        async def scenario():
            mesh = TreeMesh(5, tmp_path)
            await mesh.start()
            r4, _w4 = await mesh.subscribe(4, "sub4", "t/x")
            await mesh.settle_summaries()
            _rp, wp, _ = await mesh.harnesses[2].connect("pub2", version=4)
            wp.write(pub_packet("t/x", b"hop0", qos=0, version=4))
            wp.write(pub_packet("t/x", b"hop1", qos=1, pid=3, version=4))
            await wp.drain()
            seen = await read_until_payload(r4, b"hop1")
            assert seen == [b"hop0", b"hop1"]  # both, once, in order
            await mesh.stop()

        run(scenario())

    def test_summary_gates_uninterested_edges(self, tmp_path):
        """With summaries settled, a publish matching NO remote interest
        is filtered at the origin (counted) instead of flooding the
        tree; interested publishes still forward (no false negatives)."""

        async def scenario():
            mesh = TreeMesh(5, tmp_path)
            await mesh.start()
            r4, _w4 = await mesh.subscribe(4, "sub4", "wanted/#")
            await mesh.settle_summaries()
            origin = mesh.clusters[2]
            filtered0 = origin.summary_filtered_forwards
            _rp, wp, _ = await mesh.harnesses[2].connect("pub2", version=4)
            wp.write(pub_packet("nobody/cares", b"drop me", qos=0, version=4))
            wp.write(pub_packet("wanted/t", b"keep me", qos=0, version=4))
            await wp.drain()
            seen = await read_until_payload(r4, b"keep me")
            assert seen == [b"keep me"]
            assert origin.summary_filtered_forwards > filtered0
            await mesh.stop()

        run(scenario())

    def test_retained_replicates_to_every_worker(self, tmp_path):
        """Retained state floods every edge regardless of summaries: a
        subscriber landing on ANY worker later must see it."""

        async def scenario():
            mesh = TreeMesh(5, tmp_path)
            await mesh.start()
            await mesh.settle_summaries()
            _rp, wp, _ = await mesh.harnesses[3].connect("pub3", version=4)
            wp.write(
                pub_packet("cfg/x", b"retained", qos=0, version=4, retain=True)
            )
            await wp.drain()
            await wait_for(
                lambda: all(
                    h.server.topics.retained.get("cfg/x") is not None
                    for h in mesh.harnesses
                ),
                msg="retained replication",
            )
            # a late subscriber on a different leaf gets the retained copy
            r2, _w2 = await mesh.subscribe(2, "late2", "cfg/#")
            seen = await read_until_payload(r2, b"retained")
            assert seen == [b"retained"]
            await mesh.stop()

        run(scenario())

    def test_predicate_subscriber_receives_cross_worker(self, tmp_path):
        """The ISSUE 9 seam test: a ``sensors/+/temp$GT{25}`` subscriber
        contributes its BASE filter to the edge summaries, so remote
        publishes still forward — and the predicate then gates delivery
        at the subscriber's worker (30.0 passes, 20.0 is filtered)."""

        async def scenario():
            mesh = TreeMesh(
                5, tmp_path, predicate_filters=True
            )
            await mesh.start()
            r4, _w4 = await mesh.subscribe(
                4, "pred4", "sensors/+/temp$GT{25}"
            )
            await mesh.settle_summaries()
            # the base filter (not the suffixed form) reached the blooms
            origin = mesh.clusters[2]
            assert any(
                es.bits.might_match("sensors/a/temp")
                for es in origin._edge_summaries.values()
            )
            _rp, wp, _ = await mesh.harnesses[2].connect("pub2", version=4)
            wp.write(pub_packet("sensors/a/temp", b"20.0", qos=0, version=4))
            wp.write(pub_packet("sensors/a/temp", b"30.0", qos=0, version=4))
            await wp.drain()
            seen = await read_until_payload(r4, b"30.0")
            assert seen == [b"30.0"]  # 20.0 forwarded but predicate-gated
            await mesh.stop()

        run(scenario())

    def test_shared_group_subscriber_receives_cross_worker(self, tmp_path):
        """$SHARE summarizes as its inner filter: publishes arrive on
        the inner topic space and must forward to the member's worker."""

        async def scenario():
            mesh = TreeMesh(5, tmp_path)
            await mesh.start()
            r3, _w3 = await mesh.subscribe(3, "share3", "$SHARE/g/jobs/#")
            await mesh.settle_summaries()
            _rp, wp, _ = await mesh.harnesses[1].connect("pub1", version=4)
            wp.write(pub_packet("jobs/t", b"job", qos=0, version=4))
            await wp.drain()
            seen = await read_until_payload(r3, b"job")
            assert seen == [b"job"]
            await mesh.stop()

        run(scenario())

    def test_unsubscribe_is_a_counted_delete(self, tmp_path):
        """UNSUBSCRIBE removes the filter from the local bloom (counted
        delete, not rebuild-the-world): once summaries refresh, the
        publish is filtered again at the origin."""

        async def scenario():
            mesh = TreeMesh(3, tmp_path)
            await mesh.start()
            sub = mesh.clusters[2]
            assert not sub._local_interest.bits().might_match("u/t")
            r2, w2 = await mesh.subscribe(2, "sub2", "u/t")
            await wait_for(
                lambda: sub._local_interest.bits().might_match("u/t"),
                msg="bloom add",
            )
            from mqtt_tpu.packets import (
                UNSUBSCRIBE,
                FixedHeader,
                Packet,
                encode_packet,
            )

            w2.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=UNSUBSCRIBE, qos=1),
                        protocol_version=4,
                        packet_id=2,
                        filters=[Subscription(filter="u/t")],
                    )
                )
            )
            await w2.drain()
            await wait_for(
                lambda: not sub._local_interest.bits().might_match("u/t"),
                msg="bloom delete",
            )
            await mesh.stop()

        run(scenario())


# -- duplicate suppression + loop guards --------------------------------------


def _rframe_payload(origin: str, rt: dict, frame: bytes) -> bytes:
    ob = origin.encode()
    rj = json.dumps(rt).encode()
    return (
        struct.pack(">H", len(ob)) + ob + struct.pack(">H", len(rj)) + rj + frame
    )


class TestDuplicateSuppression:
    def test_replayed_rframe_is_suppressed_and_counted(self, tmp_path):
        """The same (origin, boot, seq) arriving twice — the
        re-parenting replay shape — delivers once; the second arrival is
        a counted no-op (no delivery, no re-forward)."""

        async def scenario():
            mesh = TreeMesh(3, tmp_path)
            await mesh.start()
            r2, _w2 = await mesh.subscribe(2, "sub2", "d/x")
            await mesh.settle_summaries()
            target = mesh.clusters[2]
            ep = target.topo.epoch
            rt = {
                "e": ep.num, "eb": ep.boot, "ep": ep.proposer,
                "o": 0, "b": 424242, "s": 1,
            }
            frame = pub_packet("d/x", b"dup?", qos=0, version=4)
            payload = _rframe_payload("pub-far", rt, frame)
            suppressed0 = target.duplicates_suppressed
            target._on_rframe(0, payload)
            target._on_rframe(0, payload)  # the replay
            assert target.duplicates_suppressed == suppressed0 + 1
            seen = await read_until_payload(r2, b"dup?")
            assert seen == [b"dup?"]
            await mesh.stop()

        run(scenario())

    def test_origin_echo_is_suppressed(self, tmp_path):
        """A routed frame whose origin is THIS incarnation arriving
        back (mixed-epoch trees can route a frame to its source) must
        never re-deliver to the origin's local subscribers: the origin
        delivered at publish time and records no window entry for its
        own sends."""

        async def scenario():
            mesh = TreeMesh(3, tmp_path)
            await mesh.start()
            origin = mesh.clusters[0]
            r0, _w0 = await mesh.subscribe(0, "sub0", "echo/#")
            await mesh.settle_summaries()
            ep = origin.topo.epoch
            echo = {
                "e": ep.num, "eb": ep.boot, "ep": ep.proposer,
                "o": 0, "b": origin.boot_id, "s": 12345,
            }
            frame = pub_packet("echo/t", b"boomerang", qos=0, version=4)
            suppressed0 = origin.duplicates_suppressed
            origin._on_rframe(1, _rframe_payload("self", echo, frame))
            assert origin.duplicates_suppressed == suppressed0 + 1
            # a CANARY publish proves nothing from the echo arrived
            _rp, wp, _ = await mesh.harnesses[1].connect("pub1", version=4)
            wp.write(pub_packet("echo/t", b"canary", qos=0, version=4))
            await wp.drain()
            seen = await read_until_payload(r0, b"canary")
            assert seen == [b"canary"]
            await mesh.stop()

        run(scenario())

    def test_park_replay_restamps_full_epoch_identity(self, tmp_path):
        """_park_payload must restamp num AND boot AND proposer: the
        receiver re-forwards only on an exact triple match, so a
        replayed park carrying the dead proposal's identity would stop
        at the first hop instead of fanning down the healed subtree."""

        async def scenario():
            mesh = TreeMesh(3, tmp_path)
            await mesh.start()
            c0 = mesh.clusters[0]
            stale_rt = {"e": 1, "eb": 999, "ep": 9, "o": 0, "b": 7, "s": 3}
            head = {"origin": "x", "qos": 1, "retain": False, "rt": stale_rt}
            entry = ("P", "p/t", head, b"\x30\x05\x00\x03p/t")
            payload = c0._park_payload(entry)
            restamped = json.loads(payload.split(b"\x00", 1)[0])["rt"]
            ep = c0.topo.epoch
            assert restamped["e"] == ep.num
            assert restamped["eb"] == ep.boot
            assert restamped["ep"] == ep.proposer
            # the exactly-once key survives the restamp untouched
            assert (restamped["o"], restamped["b"], restamped["s"]) == (0, 7, 3)
            await mesh.stop()

        run(scenario())

    def test_stale_epoch_frame_delivers_and_reforwards_live_tree(
        self, tmp_path
    ):
        """A frame stamped under a dead tree reaches local subscribers
        AND re-forwards down the LIVE tree's edges — dropping it would
        starve the downstream subtree every time a re-election races an
        in-flight frame (the 32-worker drill's loss mode before this
        was fixed). The (origin, boot, seq) window, not epoch
        agreement, is the loop guard: a second arrival anywhere is a
        counted no-op."""

        async def scenario():
            mesh = TreeMesh(5, tmp_path)
            await mesh.start()
            # worker 1 is interior: its children (3, 4) receive
            # re-forwards of anything arriving from the root side
            interior = mesh.clusters[1]
            r1, _w1 = await mesh.subscribe(1, "sub1", "s/x")
            r3, _w3 = await mesh.subscribe(3, "sub3", "s/x")
            await mesh.settle_summaries()
            stale = {
                "e": 999, "eb": 1, "ep": 0,  # no tree this worker runs
                "o": 0, "b": 99, "s": 50,
            }
            frame = pub_packet("s/x", b"stale", qos=0, version=4)
            stale0 = interior.stale_epoch_frames
            interior._on_rframe(0, _rframe_payload("pub-x", stale, frame))
            assert interior.stale_epoch_frames == stale0 + 1
            seen = await read_until_payload(r1, b"stale")
            assert seen == [b"stale"]  # delivered locally...
            seen3 = await read_until_payload(r3, b"stale")
            assert seen3 == [b"stale"]  # ...AND routed down the live tree
            # replaying the same (origin, boot, seq) is suppressed:
            # conservative re-forwarding cannot loop or double-deliver
            suppressed0 = interior.duplicates_suppressed
            interior._on_rframe(0, _rframe_payload("pub-x", stale, frame))
            assert interior.duplicates_suppressed == suppressed0 + 1
            await mesh.stop()

        run(scenario())


# -- per-edge health: sever -> re-election -> exactly-once heal ---------------


class TestTreePartition:
    def test_suspect_edge_parks_then_heal_replays_exactly_once(self, tmp_path):
        """An asymmetric partition (pongs lost) walks the edge to
        SUSPECT; QoS1 forwards park in the byte-budget buffer; the heal
        replays them exactly once — the subscriber sees each payload
        once, and the replay counter matches the park depth."""

        async def scenario():
            mesh = TreeMesh(3, tmp_path, partition_pings=600)
            # 0 -- 1 and 0 -- 2 at degree 2: sever the 0->2 return path
            await mesh.start()
            r2, _w2 = await mesh.subscribe(2, "sub2", "p/#")
            await mesh.settle_summaries()
            origin = mesh.clusters[0]
            release = asymmetric_partition(origin, 2)
            await wait_for(
                lambda: origin._health_for(2).state == PEER_SUSPECT,
                msg="suspect",
            )
            _rp, wp, _ = await mesh.harnesses[0].connect("pub0", version=4)
            for i in range(5):
                wp.write(
                    pub_packet("p/t", f"m{i}".encode(), qos=1, pid=10 + i,
                               version=4)
                )
            await wp.drain()
            await wait_for(
                lambda: len(origin._health_for(2).park) == 5, msg="parked"
            )
            replayed0 = origin.replayed_forwards
            release()
            await wait_for(
                lambda: origin._health_for(2).state == PEER_UP, msg="heal"
            )
            seen = await read_until_payload(r2, b"m4")
            assert seen == [b"m0", b"m1", b"m2", b"m3", b"m4"]
            assert origin.replayed_forwards == replayed0 + 5
            assert not origin._health_for(2).park
            await mesh.stop()

        run(scenario())

    def test_interior_death_scoped_re_election_and_reroute(self, tmp_path):
        """Killing the interior worker orphans its subtree: survivors
        re-elect WITHOUT it (strictly-greater epoch), the orphans
        re-parent, and leaf-to-leaf delivery works under the new tree —
        with zero duplicate deliveries across the transition."""

        async def scenario():
            mesh = TreeMesh(5, tmp_path)
            await mesh.start()
            r4, _w4 = await mesh.subscribe(4, "sub4", "e/#")
            await mesh.settle_summaries()
            survivors = [c for c in mesh.clusters if c.worker_id != 1]
            ep0 = {c.worker_id: c.topo.epoch_num() for c in survivors}
            await mesh.clusters[1].stop()
            await wait_for(
                lambda: all(
                    c.topo.epoch_num() > ep0[c.worker_id]
                    and 1 not in c.topo.members()
                    for c in survivors
                ),
                timeout=30,
                msg="scoped re-election",
            )
            # concurrent proposals (several survivors detect the death
            # independently) must CONVERGE on one winner: the strict
            # total order picks it, adoption re-floods carry it
            await wait_for(
                lambda: len({c.topo.epoch for c in survivors}) == 1,
                timeout=30,
                msg="epoch convergence",
            )
            await wait_for(
                lambda: all(
                    all(p in c._writers for p in c.topo.neighbors())
                    for c in survivors
                ),
                timeout=30,
                msg="post-election links",
            )
            _rp, wp, _ = await mesh.harnesses[2].connect("pub2", version=4)
            wp.write(pub_packet("e/t", b"post-heal", qos=0, version=4))
            await wp.drain()
            seen = await read_until_payload(r4, b"post-heal")
            assert seen == [b"post-heal"]
            await mesh.stop(skip=(1,))

        run(scenario())

    def test_flapped_link_heals_without_duplicates(self, tmp_path):
        """A hard-severed live edge (connection reset) re-dials and
        heals; traffic published after the heal arrives exactly once."""

        async def scenario():
            mesh = TreeMesh(3, tmp_path)
            await mesh.start()
            r2, _w2 = await mesh.subscribe(2, "sub2", "f/#")
            await mesh.settle_summaries()
            origin = mesh.clusters[0]
            assert sever_peer_link(origin, 2)
            await wait_for(
                lambda: 2 in origin._writers
                and origin._health_for(2).state == PEER_UP,
                msg="re-dial heal",
            )
            _rp, wp, _ = await mesh.harnesses[0].connect("pub0", version=4)
            wp.write(pub_packet("f/t", b"after-flap", qos=1, pid=7, version=4))
            await wp.drain()
            seen = await read_until_payload(r2, b"after-flap")
            assert seen == [b"after-flap"]
            await mesh.stop()

        run(scenario())

    def test_restarted_incarnation_forces_new_epoch(self, tmp_path):
        """A peer HELLO with a MOVED boot nonce (restarted incarnation)
        must advance the epoch — its dead tree can never be resurrected
        by stale announcements."""

        async def scenario():
            mesh = TreeMesh(3, tmp_path)
            await mesh.start()
            c0 = mesh.clusters[0]
            ep0 = c0.topo.epoch_num()
            boot1 = c0.topo.members()[1]
            assert boot1  # learned from the live HELLO/SYNC
            c0._member_contact(1, boot1 + 1)  # same id, new incarnation
            assert c0.topo.epoch_num() > ep0
            assert c0.topo.members()[1] == boot1 + 1
            await mesh.stop()

        run(scenario())


# -- per-signal pressure gossip (ISSUE 9 satellite) ---------------------------


class TestPerSignalGossip:
    def test_signal_breakdown_folds_and_decays(self):
        clock = [100.0]
        sig = PeerPressureSignal(
            weight=0.9, ttl_s=10.0, clock=lambda: clock[0]
        )
        sig.observe(1, 0, 0.4, signals={"staging": 0.4, "rss": 0.1})
        sig.observe(2, 0, 0.8, signals={"staging": 0.2, "backlog": 0.8})
        assert sig.signal_names() == {"staging", "rss", "backlog"}
        assert sig.signal_value("staging") == pytest.approx(0.4)
        vals = sig.signal_values()
        assert vals["backlog"] == pytest.approx(0.8)
        clock[0] += 5.0  # half the TTL: linear decay to half weight
        assert sig.signal_value("staging") == pytest.approx(0.2)
        clock[0] += 6.0  # past the TTL: stale adverts contribute zero
        assert sig.signal_values() == {}
        sig.observe(3, 0, 0.5, signals={"staging": 0.5})
        sig.forget(3)
        assert sig.signal_value("staging") == 0.0

    def test_gossip_carries_breakdown_to_peer_gauges(self, tmp_path):
        """_on_gossip feeds the advert's per-signal map into the
        governor's PeerPressureSignal and registers one labeled gauge
        per signal name — the operator's WHY view."""
        from tests.test_federation import _bare_cluster

        c, gov = _bare_cluster(tmp_path)
        payload = json.dumps(
            {"s": 1, "p": 0.7, "sig": {"staging": 0.7, "rss": 0.3}}
        ).encode()
        c._on_gossip(2, payload)
        sig = gov.peer_signal
        assert sig.signal_value("staging") == pytest.approx(0.7)
        assert c._peer_advert_sigs[2] == {"staging": 0.7, "rss": 0.3}
        # the governor's $SYS gauge map exposes the breakdown
        assert gov.gauges()["peers_signal/staging"] == pytest.approx(0.7)

    def test_malformed_breakdown_is_ignored(self, tmp_path):
        from tests.test_federation import _bare_cluster

        c, _gov = _bare_cluster(tmp_path)
        c._on_gossip(2, json.dumps({"s": 0, "p": 0.1, "sig": "junk"}).encode())
        assert 2 not in c._peer_advert_sigs  # scalar advert still applied
        assert c._peer_adverts[2][1] == pytest.approx(0.1)

    def test_tree_advert_folds_subtree_excluding_target_edge(self, tmp_path):
        """The advert sent on edge E is the elementwise max of the local
        posture and every OTHER edge's advert — E's own contribution is
        excluded (re-advertising a peer's pressure back to it would
        echo), and stale adverts age out of the fold."""

        async def scenario():
            mesh = TreeMesh(3, tmp_path)
            await mesh.start()
            c0 = mesh.clusters[0]  # root, edges to 1 and 2
            c0._peer_adverts[1] = (1, 0.9, time.monotonic())
            c0._peer_advert_sigs[1] = {"staging": 0.9}
            c0._peer_adverts[2] = (0, 0.2, time.monotonic())
            c0._peer_advert_sigs[2] = {"rss": 0.2}
            toward_2 = json.loads(c0._advert_payload(exclude=2))
            assert toward_2["s"] == 1  # worker 1's THROTTLE folds through
            assert toward_2["p"] == pytest.approx(0.9)
            assert toward_2["sig"]["staging"] == pytest.approx(0.9)
            assert "rss" not in toward_2["sig"]  # 2's own echo excluded
            toward_1 = json.loads(c0._advert_payload(exclude=1))
            assert toward_1["sig"].get("rss", 0.0) == pytest.approx(0.2)
            assert "staging" not in toward_1["sig"]
            # a stale advert ages out of the fold entirely
            c0._peer_adverts[1] = (
                1, 0.9, time.monotonic() - c0.advert_ttl_s - 1
            )
            toward_2b = json.loads(c0._advert_payload(exclude=2))
            assert toward_2b["p"] < 0.9
            await mesh.stop()

        run(scenario())

    def test_sys_topics_carry_tree_gauges(self, tmp_path):
        """$SYS publishes the tree epoch/links/duplicate counters (the
        drill scrapes these from the outside)."""

        async def scenario():
            mesh = TreeMesh(3, tmp_path)
            await mesh.start()
            srv = mesh.harnesses[0].server
            srv.publish_sys_topics()
            ret = srv.topics.retained
            pfx = "$SYS/broker/cluster/tree/"
            for suffix in (
                "epoch", "neighbors", "links", "re_elections",
                "duplicates_suppressed", "stale_epoch_frames",
                "summary_filtered", "summary_passthrough",
            ):
                assert ret.get(pfx + suffix) is not None, suffix
            assert ret.get("$SYS/broker/cluster/control_bytes") is not None
            await mesh.stop()

        run(scenario())


# -- ISSUE 17: root-failure fast path ----------------------------------------


class TestRootFailover:
    def test_successor_promotes_without_full_re_election(self, tmp_path):
        """Killing the ROOT takes the fast path: the pre-agreed
        successor (second-lowest live id, announced with every epoch)
        promotes at its own SUSPECT transition and floods the new epoch
        — no PARTITIONED wait, no scoped-re-election blackout. With
        partition_pings cranked out of reach, the fast path is the ONLY
        way the mesh can converge, so convergence proves it fired."""

        async def scenario():
            mesh = TreeMesh(5, tmp_path, partition_pings=600)
            await mesh.start()
            c1 = mesh.clusters[1]
            assert mesh.clusters[0].topo.root() == 0
            assert c1.topo.successor() == 1  # the pre-agreed successor
            r4, _w4 = await mesh.subscribe(4, "sub4", "ft/#")
            await mesh.settle_summaries()

            await mesh.clusters[0].stop()  # SIGKILL-shaped: root gone
            survivors = mesh.clusters[1:]
            await wait_for(
                lambda: c1.root_failovers == 1,
                timeout=30,
                msg="successor promotion",
            )
            # the promotion window (propose -> epoch flooded) is bounded
            # well inside the acceptance budget of 2 ping intervals
            assert 0.0 < c1.root_failover_last_s < 2 * c1.PING_INTERVAL_S
            await wait_for(
                lambda: all(
                    c.topo.root() == 1 and 0 not in c.topo.members()
                    for c in survivors
                )
                and len({c.topo.epoch for c in survivors}) == 1,
                timeout=30,
                msg="one epoch under the promoted root",
            )
            # the NEXT successor is re-agreed from the shrunken view
            assert c1.topo.successor() == 2
            await wait_for(
                lambda: all(
                    all(p in c._writers for p in c.topo.neighbors())
                    for c in survivors
                ),
                timeout=30,
                msg="post-failover links",
            )
            # routing works under the promoted root's tree
            _rp, wp, _ = await mesh.harnesses[2].connect("pub2", version=4)
            wp.write(pub_packet("ft/x", b"post-failover", qos=0, version=4))
            await wp.drain()
            seen = await read_until_payload(r4, b"post-failover")
            assert seen == [b"post-failover"]
            await mesh.stop(skip=(0,))

        run(scenario())

    def test_non_successor_never_takes_the_fast_path(self, tmp_path):
        """Only the agreed successor may promote: any other worker
        observing the root SUSPECT must wait for the ordinary
        re-election machinery (never two competing fast promotions)."""

        async def scenario():
            mesh = TreeMesh(3, tmp_path, partition_pings=600)
            await mesh.start()
            c2 = mesh.clusters[2]
            before = c2.topo.epoch
            c2._maybe_promote_root(0)  # root suspect, but 2 != successor
            assert c2.root_failovers == 0
            assert c2.topo.epoch == before
            # and the successor ignores a non-root suspect the same way
            c1 = mesh.clusters[1]
            c1._maybe_promote_root(2)
            assert c1.root_failovers == 0
            await mesh.stop()

        run(scenario())

    def test_epoch_announcement_carries_the_successor(self, tmp_path):
        """The non-digest epoch announcement advertises the pre-agreed
        successor — observability for operators and the drill scrape;
        receivers recompute it from the member view."""

        async def scenario():
            mesh = TreeMesh(3, tmp_path)
            await mesh.start()
            c0 = mesh.clusters[0]
            sent = []
            orig = c0._send_nowait
            c0._send_nowait = (
                lambda p, w, t, b: sent.append((p, t, b)) or orig(p, w, t, b)
            )
            try:
                c0._announce_epoch()
                from mqtt_tpu.cluster import _T_EPOCH

                bodies = [
                    json.loads(b.decode())
                    for _p, t, b in sent
                    if t == _T_EPOCH
                ]
                assert bodies and all(b.get("sc") == 1 for b in bodies)
            finally:
                c0._send_nowait = orig
            await mesh.stop()

        run(scenario())


# -- ISSUE 17: predicate push-down over edge summaries ------------------------


class TestPredicatePushdown:
    def test_edge_filters_failing_payloads_and_passes_matching(self, tmp_path):
        """A remote ``pp/#$GT{v:50}`` subscriber interns its predicate
        digest into the edge summaries: a publish whose payload PROVABLY
        fails the predicate is filtered at the ORIGIN edge (counted),
        while a passing payload still forwards and delivers — false
        negatives impossible, same contract as the blooms."""

        async def scenario():
            mesh = TreeMesh(5, tmp_path)
            await mesh.start()
            r4, _w4 = await mesh.subscribe(4, "pred4", "pp/#$GT{v:50}")
            await mesh.settle_summaries()
            origin = mesh.clusters[2]
            before = origin.summary_predicate_filtered_forwards
            _rp, wp, _ = await mesh.harnesses[2].connect("pub2", version=4)

            # digest folds propagate transitively (4 -> 1 -> 0 -> 2), one
            # presence round per hop: keep publishing provably-failing
            # payloads until the origin's edge gate starts cutting them.
            # every one of these either dies at the origin (counted) or
            # is predicate-gated at worker 4 — NEVER delivered.
            async def _edge_filtering():
                wp.write(
                    pub_packet("pp/x", b'{"v": 10}', qos=0, version=4)
                )
                await wp.drain()
                return origin.summary_predicate_filtered_forwards > before

            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                if await _edge_filtering():
                    break
                await asyncio.sleep(0.05)
            assert origin.summary_predicate_filtered_forwards > before

            wp.write(pub_packet("pp/x", b'{"v": 90}', qos=0, version=4))
            await wp.drain()
            seen = await read_until_payload(r4, b'{"v": 90}')
            assert seen == [b'{"v": 90}']  # zero failing payloads leaked
            await mesh.stop()

        run(scenario())

    def test_unknown_digest_is_conservative_passthrough(self, tmp_path):
        """An edge whose summary advertises predicate interest but whose
        digest plane is unknown (old sender / cap overflow) must forward
        everything — stale knowledge can only cost bytes, never a
        delivery."""

        async def scenario():
            mesh = TreeMesh(3, tmp_path)
            await mesh.start()
            r2, _w2 = await mesh.subscribe(2, "pd2", "pq/#$GT{v:50}")
            await mesh.settle_summaries()
            origin = mesh.clusters[1]
            # poison the digest plane on every edge: unknown, not empty
            for es in origin._edge_summaries.values():
                es.digests = None
            _rp, wp, _ = await mesh.harnesses[1].connect("pub1", version=4)
            wp.write(pub_packet("pq/x", b'{"v": 90}', qos=0, version=4))
            await wp.drain()
            seen = await read_until_payload(r2, b'{"v": 90}')
            assert seen == [b'{"v": 90}']
            await mesh.stop()

        run(scenario())


# -- ISSUE 17: shaped links + rapid-flap exactly-once -------------------------


class TestShapedLinks:
    def test_rapid_flap_replays_each_parked_frame_once(self, tmp_path):
        """A peer flapping UP -> SUSPECT -> UP repeatedly within one
        park window replays each parked frame AT MOST ONCE across all
        heals: frames parked before the first heal must not ride the
        second heal's replay. Run over seeded shaped links (delay +
        jitter) so the WAN-ish reordering pressure is part of the
        regression, reproducibly."""

        async def scenario():
            from mqtt_tpu.faults import LinkShape, shape_cluster_links

            mesh = TreeMesh(3, tmp_path, partition_pings=600)
            await mesh.start()
            shape = LinkShape(seed=7, delay_s=0.004, jitter_s=0.002)
            releases = [
                shape_cluster_links(c, shape) for c in mesh.clusters
            ]
            r2, _w2 = await mesh.subscribe(2, "sub2", "rf/#")
            await mesh.settle_summaries()
            origin = mesh.clusters[0]
            # the shaper delays the post-subscribe summary push: wait for
            # the INTEREST (not just a fresh epoch stamp) before cutting
            # the link, or the partition swallows it and nothing parks
            await wait_for(
                lambda: 2 in origin._edge_summaries
                and origin._edge_summaries[2].bits.might_match("rf/t"),
                msg="interest propagated",
            )
            replayed0 = origin.replayed_forwards
            _rp, wp, _ = await mesh.harnesses[0].connect("pub0", version=4)

            # flap 1: park 5 under SUSPECT, heal, each replays once
            cut = asymmetric_partition(origin, 2)
            await wait_for(
                lambda: origin._health_for(2).state == PEER_SUSPECT,
                msg="suspect #1",
            )
            for i in range(5):
                wp.write(
                    pub_packet("rf/t", f"m{i}".encode(), qos=1, pid=20 + i,
                               version=4)
                )
            await wp.drain()
            await wait_for(
                lambda: len(origin._health_for(2).park) == 5, msg="park #1"
            )
            cut()
            await wait_for(
                lambda: origin._health_for(2).state == PEER_UP,
                msg="heal #1",
            )
            seen1 = await read_until_payload(r2, b"m4")
            assert seen1 == [b"m0", b"m1", b"m2", b"m3", b"m4"]

            # flap 2, same park window: ONLY the newly parked frames
            # may replay — m0..m4 are spent
            cut = asymmetric_partition(origin, 2)
            await wait_for(
                lambda: origin._health_for(2).state == PEER_SUSPECT,
                msg="suspect #2",
            )
            for i in range(5, 8):
                wp.write(
                    pub_packet("rf/t", f"m{i}".encode(), qos=1, pid=20 + i,
                               version=4)
                )
            await wp.drain()
            await wait_for(
                lambda: len(origin._health_for(2).park) == 3, msg="park #2"
            )
            cut()
            await wait_for(
                lambda: origin._health_for(2).state == PEER_UP,
                msg="heal #2",
            )
            seen2 = await read_until_payload(r2, b"m7")
            assert seen2 == [b"m5", b"m6", b"m7"]  # no m0..m4 re-replay
            assert origin.replayed_forwards == replayed0 + 8
            assert not origin._health_for(2).park
            for rel in releases:
                rel()
            await mesh.stop()

        run(scenario())

    def test_link_shape_is_deterministic_per_seed(self):
        """Two shapers built from the same LinkShape drop/delay the same
        frames — the WAN schedule is part of the test's identity."""
        import random

        from mqtt_tpu.faults import LinkShape

        shape = LinkShape(seed=11, loss=0.3)
        rng_a = random.Random((shape.seed << 24) ^ (0 << 12) ^ 2)
        rng_b = random.Random((shape.seed << 24) ^ (0 << 12) ^ 2)
        assert [rng_a.random() for _ in range(64)] == [
            rng_b.random() for _ in range(64)
        ]
        # distinct edges draw from distinct streams
        rng_c = random.Random((shape.seed << 24) ^ (1 << 12) ^ 2)
        assert [rng_a.random() for _ in range(8)] != [
            rng_c.random() for _ in range(8)
        ]


# -- ISSUE 17: TCP / TLS peer transport ---------------------------------------


def _free_ports(n):
    import socket as _socket

    socks = [_socket.socket() for _ in range(n)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


class TestTcpTransport:
    def test_tcp_mesh_routes_cross_worker(self, tmp_path):
        """The same mesh over TCP peer links (pinned per-worker
        addresses, keepalive armed): multi-hop publish/subscribe
        delivers exactly once — bit-identical semantics to unix."""

        async def scenario():
            ports = _free_ports(3)
            addrs = {i: f"127.0.0.1:{p}" for i, p in enumerate(ports)}
            mesh = TreeMesh(
                3, tmp_path,
                cluster_transport="tcp",
                cluster_peer_addrs=addrs,
                cluster_keepalive_s=30.0,
                cluster_connect_timeout_s=2.0,
            )
            await mesh.start()
            for c in mesh.clusters:
                assert c.transport == "tcp"
            r2, _w2 = await mesh.subscribe(2, "sub2", "tcp/#")
            await mesh.settle_summaries()
            _rp, wp, _ = await mesh.harnesses[1].connect("pub1", version=4)
            wp.write(pub_packet("tcp/t", b"over-tcp", qos=1, pid=5, version=4))
            await wp.drain()
            seen = await read_until_payload(r2, b"over-tcp")
            assert seen == [b"over-tcp"]
            await mesh.stop()

        run(scenario())

    @pytest.mark.skipif(
        __import__("shutil").which("openssl") is None,
        reason="openssl binary unavailable: cannot mint a test cert",
    )
    def test_tls_mesh_routes_cross_worker(self, tmp_path):
        """TLS peer links with CA verification BOTH directions: a
        self-signed cert doubles as the CA, every worker presents it,
        and routed delivery still works — the handshake is in the path,
        not mocked."""
        import subprocess

        cert = tmp_path / "mesh-cert.pem"
        key = tmp_path / "mesh-key.pem"
        # no -addext: -x509 already stamps basicConstraints=CA:TRUE, and
        # a DUPLICATE extension makes OpenSSL reject the chain
        subprocess.run(
            [
                "openssl", "req", "-x509", "-newkey", "rsa:2048",
                "-nodes", "-keyout", str(key), "-out", str(cert),
                "-days", "1", "-subj", "/CN=mqtt-tpu-mesh",
            ],
            check=True, capture_output=True,
        )

        async def scenario():
            ports = _free_ports(3)
            addrs = {i: f"127.0.0.1:{p}" for i, p in enumerate(ports)}
            mesh = TreeMesh(
                3, tmp_path,
                cluster_transport="tcp",
                cluster_peer_addrs=addrs,
                cluster_tls_cert=str(cert),
                cluster_tls_key=str(key),
                cluster_tls_ca=str(cert),
            )
            await mesh.start()
            r2, _w2 = await mesh.subscribe(2, "sub2", "tls/#")
            await mesh.settle_summaries()
            _rp, wp, _ = await mesh.harnesses[1].connect("pub1", version=4)
            wp.write(pub_packet("tls/t", b"over-tls", qos=0, version=4))
            await wp.drain()
            seen = await read_until_payload(r2, b"over-tls")
            assert seen == [b"over-tls"]
            await mesh.stop()

        run(scenario())

    def test_transport_env_round_trip(self, tmp_path):
        from mqtt_tpu.cluster import worker_env

        env = worker_env(
            2, 4, str(tmp_path), topology="tree", degree=2,
            transport="tcp", base_port=39000,
        )
        assert env["MQTT_TPU_CLUSTER_TRANSPORT"] == "tcp"
        assert env["MQTT_TPU_CLUSTER_BASE_PORT"] == "39000"
        # unix mode (the default) sets neither
        env_u = worker_env(0, 2, str(tmp_path))
        assert "MQTT_TPU_CLUSTER_TRANSPORT" not in env_u
        assert "MQTT_TPU_CLUSTER_BASE_PORT" not in env_u
